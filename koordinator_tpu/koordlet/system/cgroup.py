"""Cgroup file registry with v1<->v2 mapping (reference:
``util/system/cgroup_resource.go`` — the table of every known cgroup knob —
plus ``cgroup.go`` read/write helpers).

A :class:`CgroupResource` names one kernel knob once; the active
:class:`~.config.SystemConfig` decides which filename/encoding it maps to.
Values cross the API as strings exactly as they'd be written to the kernel
file; converters translate between v1 and v2 encodings (e.g. cpu shares <->
cpu.weight, cfs quota/period <-> "max 100000").
"""

from __future__ import annotations

import dataclasses
import enum
import os
from typing import Callable, Optional

from koordinator_tpu.koordlet.system.config import SystemConfig, get_config

CGROUP_MAX = "max"
#: v1 "unlimited" encodings
V1_UNLIMITED = {"-1", "9223372036854771712", "9223372036854775807"}


class CgroupVersion(enum.IntEnum):
    V1 = 1
    V2 = 2


def shares_to_weight(shares: int) -> int:
    """Kernel mapping cpu.shares (v1, 2..262144) -> cpu.weight (v2, 1..10000)."""
    return 1 + ((shares - 2) * 9999) // 262142


def weight_to_shares(weight: int) -> int:
    return 2 + ((weight - 1) * 262142) // 9999


def _range_validator(
    lo: int, hi: int, allow_unlimited: bool = False
) -> Callable[[str], bool]:
    """Accept integers in [lo, hi]; the 'max'/-1 unlimited sentinels only for
    limit-style knobs that declare them (weight/ratio knobs must reject -1,
    or the v1->v2 conversion would emit out-of-range kernel values)."""

    def validate(value: str) -> bool:
        if allow_unlimited and (value == CGROUP_MAX or value in V1_UNLIMITED):
            return True
        try:
            return lo <= int(value) <= hi
        except ValueError:
            return False

    return validate


def _any(value: str) -> bool:
    return True


@dataclasses.dataclass(frozen=True)
class CgroupResource:
    """One kernel knob, version-agnostic."""

    name: str                 # canonical resource name, e.g. "cpu.cfs_quota"
    subsystem: str            # v1 subsystem dir ("cpu", "memory", "cpuset", "blkio")
    v1_file: str
    v2_file: str              # "" = not available on v2
    validator: Callable[[str], bool] = _any
    read_only: bool = False
    #: translate a canonical (v1-shaped) value into the v2 file encoding.
    to_v2: Optional[Callable[[str], str]] = None
    #: translate a v2 file content back to the canonical encoding.
    from_v2: Optional[Callable[[str], str]] = None

    def filename(self, version: CgroupVersion) -> str:
        return self.v1_file if version == CgroupVersion.V1 else self.v2_file

    def supported(self, version: CgroupVersion) -> bool:
        return bool(self.filename(version))


def _quota_to_v2(quota: str) -> str:
    # v2 cpu.max holds "QUOTA PERIOD"; we keep period untouched by writing the
    # first field only when the file is round-tripped through read-modify-write
    # in cgroup_update below. Canonical value here is the quota alone.
    if quota in V1_UNLIMITED or quota == CGROUP_MAX:
        return CGROUP_MAX
    return quota


def _quota_from_v2(content: str) -> str:
    field = content.split()[0] if content.split() else CGROUP_MAX
    return "-1" if field == CGROUP_MAX else field


def _shares_to_v2(shares: str) -> str:
    return str(shares_to_weight(int(shares)))


def _weight_from_v2(weight: str) -> str:
    return str(weight_to_shares(int(weight)))


def _memlimit_to_v2(limit: str) -> str:
    return CGROUP_MAX if limit in V1_UNLIMITED else limit


def _memlimit_from_v2(content: str) -> str:
    return "-1" if content == CGROUP_MAX else content


# ---- the registry (cgroup_resource.go DefaultRegistry) ----------------------

CPU_CFS_QUOTA = CgroupResource(
    "cpu.cfs_quota", "cpu", "cpu.cfs_quota_us", "cpu.max",
    _range_validator(-1, 10**9, allow_unlimited=True), to_v2=_quota_to_v2, from_v2=_quota_from_v2,
)
CPU_CFS_PERIOD = CgroupResource(
    "cpu.cfs_period", "cpu", "cpu.cfs_period_us", "",
    _range_validator(1000, 10**6),
)
CPU_CFS_BURST = CgroupResource(
    "cpu.cfs_burst", "cpu", "cpu.cfs_burst_us", "cpu.max.burst",
    _range_validator(0, 10**9),
)
CPU_SHARES = CgroupResource(
    "cpu.shares", "cpu", "cpu.shares", "cpu.weight",
    _range_validator(2, 262144), to_v2=_shares_to_v2, from_v2=_weight_from_v2,
)
CPU_BVT_WARP_NS = CgroupResource(  # group identity (Anolis kernel)
    "cpu.bvt_warp_ns", "cpu", "cpu.bvt_warp_ns", "cpu.bvt_warp_ns",
    _range_validator(-1, 2),
)
NET_CLS_CLASSID = CgroupResource(  # tc classful shaping handle (v1 only)
    "net_cls.classid", "net_cls", "net_cls.classid", "",
    _range_validator(0, 2**32 - 1),
)
CPU_IDLE = CgroupResource(
    "cpu.idle", "cpu", "cpu.idle", "cpu.idle", _range_validator(0, 1),
)
CPU_STAT = CgroupResource("cpu.stat", "cpu", "cpu.stat", "cpu.stat", read_only=True)
CPUACCT_USAGE = CgroupResource(
    "cpuacct.usage", "cpuacct", "cpuacct.usage", "", read_only=True,
)
CPUSET_CPUS = CgroupResource(
    "cpuset.cpus", "cpuset", "cpuset.cpus", "cpuset.cpus",
)
CPUSET_CPUS_EFFECTIVE = CgroupResource(
    "cpuset.cpus.effective", "cpuset", "cpuset.effective_cpus",
    "cpuset.cpus.effective", read_only=True,
)
CPUSET_MEMS = CgroupResource("cpuset.mems", "cpuset", "cpuset.mems", "cpuset.mems")
MEMORY_LIMIT = CgroupResource(
    "memory.limit", "memory", "memory.limit_in_bytes", "memory.max",
    _range_validator(-1, 1 << 62, allow_unlimited=True), to_v2=_memlimit_to_v2, from_v2=_memlimit_from_v2,
)
MEMORY_SOFT_LIMIT = CgroupResource(
    "memory.soft_limit", "memory", "memory.soft_limit_in_bytes", "memory.high",
    _range_validator(-1, 1 << 62, allow_unlimited=True), to_v2=_memlimit_to_v2, from_v2=_memlimit_from_v2,
)
MEMORY_MIN = CgroupResource(
    "memory.min", "memory", "memory.min", "memory.min",
    _range_validator(0, 1 << 62, allow_unlimited=True),
)
MEMORY_LOW = CgroupResource(
    "memory.low", "memory", "memory.low", "memory.low",
    _range_validator(0, 1 << 62, allow_unlimited=True),
)
MEMORY_HIGH = CgroupResource(
    "memory.high", "memory", "memory.high", "memory.high",
    _range_validator(0, 1 << 62, allow_unlimited=True), to_v2=_memlimit_to_v2, from_v2=_memlimit_from_v2,
)
MEMORY_WMARK_RATIO = CgroupResource(  # async reclaim watermark (Anolis)
    "memory.wmark_ratio", "memory", "memory.wmark_ratio", "memory.wmark_ratio",
    _range_validator(0, 100),
)
MEMORY_WMARK_SCALE_FACTOR = CgroupResource(
    "memory.wmark_scale_factor", "memory", "memory.wmark_scale_factor",
    "memory.wmark_scale_factor", _range_validator(1, 1000),
)
MEMORY_WMARK_MIN_ADJ = CgroupResource(
    "memory.wmark_min_adj", "memory", "memory.wmark_min_adj",
    "memory.wmark_min_adj", _range_validator(-25, 50),
)
MEMORY_PRIORITY = CgroupResource(
    "memory.priority", "memory", "memory.priority", "memory.priority",
    _range_validator(0, 12),
)
MEMORY_USE_PRIORITY_OOM = CgroupResource(
    "memory.use_priority_oom", "memory", "memory.use_priority_oom",
    "memory.use_priority_oom", _range_validator(0, 1),
)
MEMORY_OOM_GROUP = CgroupResource(
    "memory.oom.group", "memory", "", "memory.oom.group", _range_validator(0, 1),
)
MEMORY_STAT = CgroupResource(
    "memory.stat", "memory", "memory.stat", "memory.stat", read_only=True,
)
MEMORY_USAGE = CgroupResource(
    "memory.usage", "memory", "memory.usage_in_bytes", "memory.current",
    read_only=True,
)
BLKIO_WEIGHT = CgroupResource(
    "blkio.weight", "blkio", "blkio.bfq.weight", "io.bfq.weight",
    _range_validator(1, 1000),
)
BLKIO_READ_BPS = CgroupResource(
    "blkio.throttle.read_bps", "blkio", "blkio.throttle.read_bps_device", "io.max",
)
BLKIO_WRITE_BPS = CgroupResource(
    "blkio.throttle.write_bps", "blkio", "blkio.throttle.write_bps_device", "io.max",
)
BLKIO_READ_IOPS = CgroupResource(
    "blkio.throttle.read_iops", "blkio", "blkio.throttle.read_iops_device", "io.max",
)
BLKIO_WRITE_IOPS = CgroupResource(
    "blkio.throttle.write_iops", "blkio", "blkio.throttle.write_iops_device", "io.max",
)
CPU_PRESSURE = CgroupResource(
    "cpu.pressure", "cpuacct", "cpu.pressure", "cpu.pressure", read_only=True,
)
MEMORY_PRESSURE = CgroupResource(
    "memory.pressure", "cpuacct", "memory.pressure", "memory.pressure",
    read_only=True,
)
IO_PRESSURE = CgroupResource(
    "io.pressure", "cpuacct", "io.pressure", "io.pressure", read_only=True,
)
MEMORY_IDLE_PAGE_STATS = CgroupResource(  # kidled cold-page accounting
    "memory.idle_page_stats", "memory", "memory.idle_page_stats",
    "memory.idle_page_stats", read_only=True,
)

_REGISTRY: dict[str, CgroupResource] = {
    r.name: r
    for r in [
        CPU_CFS_QUOTA, CPU_CFS_PERIOD, CPU_CFS_BURST, CPU_SHARES, CPU_BVT_WARP_NS,
        CPU_IDLE, CPU_STAT, CPUACCT_USAGE, CPUSET_CPUS, CPUSET_CPUS_EFFECTIVE,
        CPUSET_MEMS, MEMORY_LIMIT, MEMORY_SOFT_LIMIT, MEMORY_MIN, MEMORY_LOW,
        MEMORY_HIGH, MEMORY_WMARK_RATIO, MEMORY_WMARK_SCALE_FACTOR,
        MEMORY_WMARK_MIN_ADJ, MEMORY_PRIORITY, MEMORY_USE_PRIORITY_OOM,
        MEMORY_OOM_GROUP, MEMORY_STAT, MEMORY_USAGE, BLKIO_WEIGHT, BLKIO_READ_BPS,
        BLKIO_WRITE_BPS, BLKIO_READ_IOPS, BLKIO_WRITE_IOPS, CPU_PRESSURE,
        MEMORY_PRESSURE, IO_PRESSURE, MEMORY_IDLE_PAGE_STATS, NET_CLS_CLASSID,
    ]
}


def known_resources() -> list[CgroupResource]:
    return list(_REGISTRY.values())


def resource_by_name(name: str) -> CgroupResource:
    return _REGISTRY[name]


# ---- read / write -----------------------------------------------------------


def _version(cfg: SystemConfig) -> CgroupVersion:
    return CgroupVersion.V2 if cfg.use_cgroup_v2 else CgroupVersion.V1


def resource_path(res: CgroupResource, rel_dir: str, cfg: SystemConfig | None = None) -> str:
    cfg = cfg or get_config()
    return cfg.cgroup_abs_path(res.subsystem, rel_dir, res.filename(_version(cfg)))


def cgroup_read(res: CgroupResource, rel_dir: str, cfg: SystemConfig | None = None) -> str:
    """Read a knob, returning the canonical (v1-shaped) encoding."""
    cfg = cfg or get_config()
    with open(resource_path(res, rel_dir, cfg)) as f:
        raw = f.read().strip()
    if _version(cfg) == CgroupVersion.V2 and res.from_v2:
        return res.from_v2(raw)
    return raw


def cgroup_write(res: CgroupResource, rel_dir: str, value: str,
                 cfg: SystemConfig | None = None) -> bool:
    """Write a canonical value to a knob; returns False if unsupported here.

    Raises ValueError on a value the validator rejects (the reference logs and
    skips; we surface it — resourceexecutor turns it into an audit record).
    """
    cfg = cfg or get_config()
    if res.read_only:
        raise ValueError(f"{res.name} is read-only")
    if not res.supported(_version(cfg)):
        return False
    if not res.validator(value):
        raise ValueError(f"invalid value {value!r} for {res.name}")
    out = value
    if _version(cfg) == CgroupVersion.V2 and res.to_v2:
        out = res.to_v2(value)
        if res is CPU_CFS_QUOTA:
            # v2 cpu.max is "QUOTA PERIOD" — preserve the existing period.
            path = resource_path(res, rel_dir, cfg)
            period = "100000"
            if os.path.exists(path):
                fields = open(path).read().split()
                if len(fields) == 2:
                    period = fields[1]
            out = f"{out} {period}"
    path = resource_path(res, rel_dir, cfg)
    with open(path, "w") as f:
        f.write(out)
    return True


def parse_stat(content: str) -> dict[str, int]:
    """Parse flat 'key value' files (cpu.stat, memory.stat)."""
    out: dict[str, int] = {}
    for line in content.splitlines():
        parts = line.split()
        if len(parts) == 2:
            try:
                out[parts[0]] = int(parts[1])
            except ValueError:
                pass
    return out
