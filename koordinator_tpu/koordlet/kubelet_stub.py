"""Kubelet read-only client stub (reference: ``statesinformer/impl/
kubelet_stub.go:40`` — fetches /pods and /configz over the kubelet's HTTPS
endpoint; the pods informer falls back to it when the apiserver watch lags).

``fetch_fn`` abstracts the transport (HTTPS client in production, fixture
JSON in tests); parsing converts the kubelet PodList payload into the agent's
:class:`~koordinator_tpu.koordlet.statesinformer.PodMeta` model.
"""

from __future__ import annotations

import json
import ssl
import time
import urllib.error
import urllib.request
from typing import Callable, Optional

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.qos import QoSClass
from koordinator_tpu.koordlet.statesinformer import ContainerMeta, PodMeta
from koordinator_tpu.metrics import KOORDLET

kubelet_request_seconds = KOORDLET.histogram(
    "kubelet_request_duration_seconds",
    "Kubelet HTTP(S) request latency by path/code "
    "(metrics.RecordKubeletRequestDuration)")

_KUBE_QOS = {
    "Guaranteed": "guaranteed",
    "Burstable": "burstable",
    "BestEffort": "besteffort",
}


def _parse_quantity(value) -> int:
    """cpu -> milli, memory -> bytes (k8s quantity strings)."""
    if isinstance(value, (int, float)):
        return int(value)
    s = str(value)
    try:
        if s.endswith("m"):
            return int(s[:-1])
        for suffix, mult in (("Ki", 1 << 10), ("Mi", 1 << 20), ("Gi", 1 << 30),
                             ("Ti", 1 << 40), ("k", 10**3), ("M", 10**6),
                             ("G", 10**9)):
            if s.endswith(suffix):
                return int(float(s[: -len(suffix)]) * mult)
        return int(float(s))
    except ValueError:
        return 0


def parse_pod_list(payload: dict) -> list[PodMeta]:
    """kubelet /pods PodList JSON -> PodMeta list."""
    out = []
    for item in payload.get("items", []):
        meta = item.get("metadata", {})
        spec = item.get("spec", {})
        status = item.get("status", {})
        labels = meta.get("labels", {}) or {}
        requests: dict[str, int] = {}
        limits: dict[str, int] = {}
        def quantity(name: str, value) -> int:
            # cpu quantities normalize to milli-cores: "2" -> 2000, "500m" -> 500
            if name == "cpu" and not str(value).endswith("m"):
                try:
                    return int(float(value) * 1000)
                except (TypeError, ValueError):
                    return 0
            return _parse_quantity(value)

        for container in spec.get("containers", []):
            resources = container.get("resources", {})
            for name, value in (resources.get("requests") or {}).items():
                requests[name] = requests.get(name, 0) + quantity(name, value)
            for name, value in (resources.get("limits") or {}).items():
                limits[name] = limits.get(name, 0) + quantity(name, value)
        containers = []
        for cs in status.get("containerStatuses", []):
            cid = cs.get("containerID", "")
            containers.append(ContainerMeta(
                name=cs.get("name", ""),
                container_id=cid.split("//")[-1] if cid else "",
            ))
        out.append(PodMeta(
            uid=meta.get("uid", ""),
            name=meta.get("name", ""),
            namespace=meta.get("namespace", "default"),
            qos_class=QoSClass.parse(labels.get(ext.LABEL_POD_QOS, "")),
            kube_qos=_KUBE_QOS.get(status.get("qosClass", ""), "besteffort"),
            priority=spec.get("priority", 0) or 0,
            phase=status.get("phase", "Pending"),
            requests=requests,
            limits=limits,
            containers=tuple(containers),
            annotations=meta.get("annotations", {}) or {},
            labels=labels,
            host_network=bool(spec.get("hostNetwork", False)),
        ))
    return out


def https_fetch_fn(
    addr: str,
    port: int,
    scheme: str = "https",
    token: Optional[str] = None,
    token_file: Optional[str] = None,
    ca_file: Optional[str] = None,
    insecure_skip_verify: bool = False,
    timeout: float = 10.0,
) -> Callable[[str], str]:
    """The production transport behind :class:`KubeletStub`: bearer-token
    TLS GET against the kubelet's read-only-or-authenticated endpoint
    (kubelet_stub.go:40 NewKubeletStub — rest.Config transport + token).

    - ``token``/``token_file``: serviceaccount bearer token (the file is
      re-read per request, matching client-go's rotating token source).
    - ``ca_file``: CA bundle to verify the kubelet's serving cert;
      ``insecure_skip_verify`` mirrors rest.Config.TLSClientConfig.Insecure
      (kubelets commonly serve self-signed certs).
    Non-200 responses raise ``OSError`` — the same failure the Go stub
    returns — so callers' fallback paths engage.
    """
    if scheme == "https":
        if insecure_skip_verify:
            ctx = ssl.create_default_context()
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        else:
            ctx = ssl.create_default_context(cafile=ca_file)
    else:
        ctx = None

    def fetch(path: str) -> str:
        url = f"{scheme}://{addr}:{port}{path}"
        request = urllib.request.Request(url)
        bearer = token
        if token_file:
            try:
                with open(token_file) as f:
                    bearer = f.read().strip()
            except OSError as e:
                # never silently downgrade to an unauthenticated (or
                # stale-static-token) request: a rotating-token read
                # failure must surface as ITS cause, not as the 401 the
                # kubelet would answer with
                raise OSError(
                    f"kubelet token file {token_file!r} unreadable: {e}"
                ) from e
        if bearer:
            request.add_header("Authorization", f"Bearer {bearer}")
        start = time.monotonic()
        code = "error"
        try:
            with urllib.request.urlopen(
                    request, timeout=timeout, context=ctx) as resp:
                code = str(resp.status)
                if resp.status != 200:
                    raise OSError(
                        f"request {url} failed, code {resp.status}")
                return resp.read().decode()
        except urllib.error.HTTPError as e:
            code = str(e.code)
            raise OSError(f"request {url} failed, code {e.code}") from e
        except urllib.error.URLError as e:
            raise OSError(f"request {url} failed: {e.reason}") from e
        finally:
            kubelet_request_seconds.observe(
                time.monotonic() - start,
                labels={"path": path, "code": code})

    return fetch


class KubeletStub:
    def __init__(self, fetch_fn: Callable[[str], str]):
        """fetch_fn(path) -> response body ('/pods', '/configz')."""
        self.fetch_fn = fetch_fn

    @classmethod
    def connect(cls, addr: str = "127.0.0.1", port: int = 10250,
                **kw) -> "KubeletStub":
        """Stub over the real HTTPS transport (kwargs per
        :func:`https_fetch_fn`)."""
        return cls(https_fetch_fn(addr, port, **kw))

    def get_all_pods(self) -> list[PodMeta]:
        body = self.fetch_fn("/pods")
        return parse_pod_list(json.loads(body))

    def get_kubelet_configz(self) -> dict:
        """kubelet config (cpuManagerPolicy, reservedCPUs...)."""
        try:
            return json.loads(self.fetch_fn("/configz")).get(
                "kubeletconfig", {}
            )
        except (json.JSONDecodeError, OSError):
            return {}
