"""Kubelet read-only client stub (reference: ``statesinformer/impl/
kubelet_stub.go:40`` — fetches /pods and /configz over the kubelet's HTTPS
endpoint; the pods informer falls back to it when the apiserver watch lags).

``fetch_fn`` abstracts the transport (HTTPS client in production, fixture
JSON in tests); parsing converts the kubelet PodList payload into the agent's
:class:`~koordinator_tpu.koordlet.statesinformer.PodMeta` model.
"""

from __future__ import annotations

import json
from typing import Callable, Optional

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.qos import QoSClass
from koordinator_tpu.koordlet.statesinformer import ContainerMeta, PodMeta

_KUBE_QOS = {
    "Guaranteed": "guaranteed",
    "Burstable": "burstable",
    "BestEffort": "besteffort",
}


def _parse_quantity(value) -> int:
    """cpu -> milli, memory -> bytes (k8s quantity strings)."""
    if isinstance(value, (int, float)):
        return int(value)
    s = str(value)
    try:
        if s.endswith("m"):
            return int(s[:-1])
        for suffix, mult in (("Ki", 1 << 10), ("Mi", 1 << 20), ("Gi", 1 << 30),
                             ("Ti", 1 << 40), ("k", 10**3), ("M", 10**6),
                             ("G", 10**9)):
            if s.endswith(suffix):
                return int(float(s[: -len(suffix)]) * mult)
        return int(float(s))
    except ValueError:
        return 0


def parse_pod_list(payload: dict) -> list[PodMeta]:
    """kubelet /pods PodList JSON -> PodMeta list."""
    out = []
    for item in payload.get("items", []):
        meta = item.get("metadata", {})
        spec = item.get("spec", {})
        status = item.get("status", {})
        labels = meta.get("labels", {}) or {}
        requests: dict[str, int] = {}
        limits: dict[str, int] = {}
        def quantity(name: str, value) -> int:
            # cpu quantities normalize to milli-cores: "2" -> 2000, "500m" -> 500
            if name == "cpu" and not str(value).endswith("m"):
                try:
                    return int(float(value) * 1000)
                except (TypeError, ValueError):
                    return 0
            return _parse_quantity(value)

        for container in spec.get("containers", []):
            resources = container.get("resources", {})
            for name, value in (resources.get("requests") or {}).items():
                requests[name] = requests.get(name, 0) + quantity(name, value)
            for name, value in (resources.get("limits") or {}).items():
                limits[name] = limits.get(name, 0) + quantity(name, value)
        containers = []
        for cs in status.get("containerStatuses", []):
            cid = cs.get("containerID", "")
            containers.append(ContainerMeta(
                name=cs.get("name", ""),
                container_id=cid.split("//")[-1] if cid else "",
            ))
        out.append(PodMeta(
            uid=meta.get("uid", ""),
            name=meta.get("name", ""),
            namespace=meta.get("namespace", "default"),
            qos_class=QoSClass.parse(labels.get(ext.LABEL_POD_QOS, "")),
            kube_qos=_KUBE_QOS.get(status.get("qosClass", ""), "besteffort"),
            priority=spec.get("priority", 0) or 0,
            phase=status.get("phase", "Pending"),
            requests=requests,
            limits=limits,
            containers=tuple(containers),
            annotations=meta.get("annotations", {}) or {},
            labels=labels,
            host_network=bool(spec.get("hostNetwork", False)),
        ))
    return out


class KubeletStub:
    def __init__(self, fetch_fn: Callable[[str], str]):
        """fetch_fn(path) -> response body ('/pods', '/configz')."""
        self.fetch_fn = fetch_fn

    def get_all_pods(self) -> list[PodMeta]:
        body = self.fetch_fn("/pods")
        return parse_pod_list(json.loads(body))

    def get_kubelet_configz(self) -> dict:
        """kubelet config (cpuManagerPolicy, reservedCPUs...)."""
        try:
            return json.loads(self.fetch_fn("/configz")).get(
                "kubeletconfig", {}
            )
        except (json.JSONDecodeError, OSError):
            return {}
