"""Device collectors: accelerator (GPU/TPU), RDMA, XPU.

Reference: ``pkg/koordlet/metricsadvisor/devices/{gpu,rdma,xpu}/`` — the GPU
collector reads NVML (utilization, memory, topology) into the metric cache
and publishes device inventory for the Device CRD; the RDMA collector lists
InfiniBand devices from sysfs; the XPU collector reads vendor-dropped device
info JSON files from a directory.

TPU-native redesign: the accelerator collector is provider-based — the
default :class:`SysfsAcceleratorProvider` reads an ``accel`` class directory
of the (relocatable) sysfs root, and :class:`JaxDeviceProvider` enumerates
the JAX runtime's devices (the TPU path: device kind, core count, HBM from
``memory_stats`` when the backend exposes them).  Collectors stay pure-host
I/O; tests run them against the fake filesystem like every other collector.
"""

from __future__ import annotations

import dataclasses
import json
import os

from koordinator_tpu.api import crds
from koordinator_tpu.koordlet import metriccache as mc


@dataclasses.dataclass
class AccelSample:
    """One accelerator's live sample."""

    uuid: str
    minor: int
    type: str = "gpu"
    core_usage_pct: float = 0.0
    mem_used_bytes: int = 0
    mem_total_bytes: int = 0
    numa_node: int = -1
    busid: str = ""
    health: bool = True


class SysfsAcceleratorProvider:
    """Reads ``<sys_root>/class/accel/<dev>/`` device dirs: files ``uuid``,
    ``minor``, ``mem_total``, ``mem_used``, ``usage_pct``, ``numa_node``
    (the fake-fs contract for tests; real vendors drop the same layout)."""

    def __init__(self, cfg):
        self.cfg = cfg

    @property
    def root(self) -> str:
        return os.path.join(self.cfg.sys_root, "class", "accel")

    def available(self) -> bool:
        return os.path.isdir(self.root)

    def _read(self, dev: str, name: str, default: str = "0") -> str:
        try:
            with open(os.path.join(self.root, dev, name)) as f:
                return f.read().strip()
        except OSError:
            return default

    def sample(self) -> list[AccelSample]:
        out = []
        for i, dev in enumerate(sorted(os.listdir(self.root))):
            if not os.path.isdir(os.path.join(self.root, dev)):
                continue
            out.append(AccelSample(
                uuid=self._read(dev, "uuid", dev),
                minor=int(self._read(dev, "minor", str(i))),
                type=self._read(dev, "type", "gpu"),
                core_usage_pct=float(self._read(dev, "usage_pct")),
                mem_used_bytes=int(self._read(dev, "mem_used")),
                mem_total_bytes=int(self._read(dev, "mem_total")),
                numa_node=int(self._read(dev, "numa_node", "-1")),
                busid=self._read(dev, "busid", ""),
                health=self._read(dev, "health", "1") == "1",
            ))
        return out


class JaxDeviceProvider:
    """Enumerates the JAX runtime's accelerators (the TPU-native path)."""

    def available(self) -> bool:
        try:
            import jax

            return len(jax.devices()) > 0
        except Exception:
            return False

    def sample(self) -> list[AccelSample]:
        import jax

        out = []
        for d in jax.devices():
            stats = {}
            try:
                stats = d.memory_stats() or {}
            except Exception:
                pass
            out.append(AccelSample(
                uuid=f"{d.platform}-{d.id}",
                minor=d.id,
                type=d.platform,  # "tpu" / "gpu"
                mem_used_bytes=int(stats.get("bytes_in_use", 0)),
                mem_total_bytes=int(stats.get("bytes_limit", 0)),
            ))
        return out


class AcceleratorCollector:
    """devices/gpu parity: per-device utilization + memory samples and
    Device-CRD inventory, gated by the Accelerators feature."""

    name = "accelerator"

    def __init__(self, deps, provider=None):
        self.d = deps
        self.provider = provider or SysfsAcceleratorProvider(deps.cfg)

    def enabled(self) -> bool:
        from koordinator_tpu.features import KOORDLET_GATES

        return KOORDLET_GATES.enabled("Accelerators") and self.provider.available()

    def collect(self) -> None:
        now = self.d.clock()
        for s in self.provider.sample():
            labels = {"minor": str(s.minor), "uuid": s.uuid, "type": s.type}
            self.d.cache.append(
                mc.ACCEL_CORE_USAGE, s.core_usage_pct, labels, ts=now
            )
            self.d.cache.append(
                mc.ACCEL_MEM_USED, float(s.mem_used_bytes), labels, ts=now
            )

    def device_infos(self) -> list[crds.DeviceInfo]:
        """Inventory for the Device CRD reporter (Infos() parity)."""
        return [
            crds.DeviceInfo(
                type=s.type, uuid=s.uuid, minor=s.minor, health=s.health,
                numa_node=s.numa_node, busid=s.busid,
                resources={
                    f"{s.type}-core": 100,
                    f"{s.type}-memory": s.mem_total_bytes,
                },
            )
            for s in self.provider.sample()
        ]


class RdmaCollector:
    """devices/rdma parity: InfiniBand device inventory from
    ``<sys_root>/class/infiniband/<dev>/`` (node_guid, ports/*/state)."""

    name = "rdma"

    def __init__(self, deps):
        self.d = deps

    @property
    def root(self) -> str:
        return os.path.join(self.d.cfg.sys_root, "class", "infiniband")

    def enabled(self) -> bool:
        from koordinator_tpu.features import KOORDLET_GATES

        return KOORDLET_GATES.enabled("RDMADevices") and os.path.isdir(self.root)

    def collect(self) -> None:
        # RDMA has no rate metrics in the reference collector; inventory only
        return None

    def device_infos(self) -> list[crds.DeviceInfo]:
        out = []
        for i, dev in enumerate(sorted(os.listdir(self.root))):
            base = os.path.join(self.root, dev)
            if not os.path.isdir(base):
                continue
            guid = ""
            try:
                with open(os.path.join(base, "node_guid")) as f:
                    guid = f.read().strip()
            except OSError:
                pass
            active = True
            ports = os.path.join(base, "ports")
            if os.path.isdir(ports):
                states = []
                for p in sorted(os.listdir(ports)):
                    try:
                        with open(os.path.join(ports, p, "state")) as f:
                            states.append("ACTIVE" in f.read().upper())
                    except OSError:
                        continue
                active = any(states) if states else True
            out.append(crds.DeviceInfo(
                type="rdma", uuid=guid or dev, minor=i, health=active,
                resources={"rdma": 100},
            ))
        return out


class XpuCollector:
    """devices/xpu parity: vendor-dropped device-info JSON files from
    ``<var_run_root>/xpu-device-infos/`` — one JSON per device with
    vendor/model/uuid/minor/memory/topology fields."""

    name = "xpu"

    def __init__(self, deps):
        self.d = deps

    @property
    def root(self) -> str:
        return os.path.join(self.d.cfg.var_run_root, "xpu-device-infos")

    def enabled(self) -> bool:
        from koordinator_tpu.features import KOORDLET_GATES

        return KOORDLET_GATES.enabled("Accelerators") and os.path.isdir(self.root)

    def collect(self) -> None:
        return None

    def device_infos(self) -> list[crds.DeviceInfo]:
        out = []
        for fn in sorted(os.listdir(self.root)):
            if not fn.endswith(".json"):
                continue
            try:
                with open(os.path.join(self.root, fn)) as f:
                    data = json.load(f)
            except (OSError, ValueError):
                continue
            out.append(crds.DeviceInfo(
                type="xpu",
                uuid=str(data.get("uuid", fn[:-5])),
                minor=int(data.get("minor", len(out))),
                health=bool(data.get("healthy", True)),
                numa_node=int(data.get("numaNode", -1)),
                busid=str(data.get("busID", "")),
                resources={
                    str(k): int(v)
                    for k, v in (data.get("resources") or {}).items()
                },
                labels={
                    "vendor": str(data.get("vendor", "")),
                    "model": str(data.get("model", "")),
                },
            ))
        return out


class HamiVGPUCollector:
    """HamiCoreVGPUMonitor parity: per-pod vGPU utilization samples from
    HAMi-core's shared-region dumps.  HAMi-core (the userspace CUDA
    intercept layer) publishes per-process vGPU core/memory accounting in
    a host-visible region; the reference's monitor samples it into the
    metric cache.  The kernel-portable rebuild reads the JSON mirror
    vendors drop under ``<var_run_root>/hami-vgpu-metrics/`` — one file
    per (device, pod) with uuid/podUID/coreUtilPct/memoryUsedBytes."""

    name = "hami-vgpu"

    def __init__(self, deps):
        self.d = deps

    @property
    def root(self) -> str:
        return os.path.join(self.d.cfg.var_run_root, "hami-vgpu-metrics")

    def enabled(self) -> bool:
        from koordinator_tpu.features import KOORDLET_GATES

        return (KOORDLET_GATES.enabled("HamiCoreVGPUMonitor")
                and os.path.isdir(self.root))

    def collect(self) -> None:
        now = self.d.clock()
        try:
            files = sorted(os.listdir(self.root))
        except OSError:
            return
        for fn in files:
            if not fn.endswith(".json"):
                continue
            try:
                with open(os.path.join(self.root, fn)) as f:
                    data = json.load(f)
            except (OSError, ValueError):
                continue
            labels = {"uuid": str(data.get("uuid", "")),
                      "pod_uid": str(data.get("podUID", ""))}
            self.d.cache.append(
                mc.HAMI_VGPU_CORE_USAGE,
                float(data.get("coreUtilPct", 0.0)), labels=labels, ts=now)
            self.d.cache.append(
                mc.HAMI_VGPU_MEM_USED,
                float(data.get("memoryUsedBytes", 0.0)), labels=labels,
                ts=now)

    def device_infos(self) -> list["crds.DeviceInfo"]:
        return []  # metrics-only: inventory comes from the GPU collector


def device_infos_to_inventory(
    infos: list["crds.DeviceInfo"],
) -> dict[str, list[dict]]:
    """Convert Device-CR DeviceInfo records into the per-type inventory the
    scheduler's DeviceManager registers ({type: [{"core", "memory",
    "group"}]} — deviceshare's nodeDevice build format).  Minor ids index
    the list; gaps pad with zero-capacity entries and unhealthy devices
    contribute zero capacity (deviceshare skips unhealthy devices)."""
    out: dict[str, list[dict]] = {}
    for info in infos:
        # Device CRs are external data: a negative minor would wrap the
        # row index, a huge one would materialize that many pad entries
        if not (0 <= int(info.minor) <= 4096):
            continue
        rows = out.setdefault(info.type, [])
        while len(rows) <= info.minor:
            rows.append({"core": 0, "memory": 0, "group": 0})
        # absent data must not create allocatable capacity: deviceshare
        # derives capacity only from reported resources, so a missing
        # {type}-core defaults to 0 (like memory), not full-capacity
        core = int(info.resources.get(f"{info.type}-core", 0))
        memory = int(info.resources.get(f"{info.type}-memory", 0))
        rows[info.minor] = {
            "core": core if info.health else 0,
            "memory": memory if info.health else 0,
            "group": max(int(info.numa_node), 0),
        }
    return out
