"""Serialized, cached, audited cgroup writer (reference:
``pkg/koordlet/resourceexecutor/executor.go`` — ``Update`` :65,
``LeveledUpdateBatch`` :114, last-value cache :240).

Semantics preserved from the reference:

- **Write suppression**: a write is skipped when the cached last-written value
  matches (the kernel file is still read first on cache miss so external
  changes are observed).
- **Leveled batch ordering**: limit *increases* must apply parent-before-child
  and *decreases* child-before-parent, or the kernel rejects the write (e.g.
  shrinking a parent cpuset below a child's). ``leveled_update_batch`` sorts
  by cgroup depth per direction.
- Every actual kernel write is audited.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Optional

from koordinator_tpu.koordlet.audit import Auditor
from koordinator_tpu.koordlet.system import cgroup as cg
from koordinator_tpu.koordlet.system.config import SystemConfig, get_config


@dataclasses.dataclass(frozen=True)
class ResourceUpdate:
    """One desired (cgroup dir, knob, value)."""

    resource: cg.CgroupResource
    rel_dir: str
    value: str

    @property
    def key(self) -> tuple[str, str]:
        return (self.resource.name, self.rel_dir)

    @property
    def depth(self) -> int:
        return self.rel_dir.rstrip("/").count("/")


@dataclasses.dataclass
class UpdateResult:
    updated: bool
    error: Optional[str] = None


class ResourceUpdateExecutor:
    def __init__(self, cfg: SystemConfig | None = None,
                 auditor: Auditor | None = None):
        self.cfg = cfg or get_config()
        self.auditor = auditor
        self._cache: dict[tuple[str, str], str] = {}
        self._lock = threading.Lock()

    def _read_current(self, update: ResourceUpdate) -> Optional[str]:
        try:
            return cg.cgroup_read(update.resource, update.rel_dir, self.cfg)
        except OSError:
            return None

    def update(self, update: ResourceUpdate) -> UpdateResult:
        """Write one knob with cache suppression."""
        with self._lock:
            cached = self._cache.get(update.key)
            if cached == update.value:
                return UpdateResult(updated=False)
            if cached is None:
                current = self._read_current(update)
                if current == update.value:
                    self._cache[update.key] = update.value
                    return UpdateResult(updated=False)
            try:
                wrote = cg.cgroup_write(
                    update.resource, update.rel_dir, update.value, self.cfg
                )
            except (OSError, ValueError) as e:
                if self.auditor:
                    self.auditor.log(
                        "cgroup", "update-failed", update.rel_dir,
                        {"resource": update.resource.name, "value": update.value,
                         "error": str(e)},
                    )
                return UpdateResult(updated=False, error=str(e))
            if not wrote:
                return UpdateResult(updated=False, error="unsupported")
            self._cache[update.key] = update.value
        if self.auditor:
            self.auditor.log(
                "cgroup", "update", update.rel_dir,
                {"resource": update.resource.name, "value": update.value},
            )
        return UpdateResult(updated=True)

    def update_batch(self, updates: list[ResourceUpdate]) -> list[UpdateResult]:
        return [self.update(u) for u in updates]

    def leveled_update_batch(
        self, updates: list[ResourceUpdate]
    ) -> list[UpdateResult]:
        """Order-sensitive batch: per knob, split into increases (parent
        first) and decreases (child first) against the current kernel value,
        then apply shallow->deep for increases and deep->shallow otherwise.

        Direction rules: numeric values compare directly ('-1'/'max' raise a
        limit, so they're increases); cpuset strings compare as sets (a
        growing cpuset must widen the parent before the child, a shrinking
        one must release children first — kernel validate_change rejects
        either done in the wrong order).
        """
        UNLIMITED = {"-1", "max", "9223372036854771712", "9223372036854775807"}

        def is_increase(u: ResourceUpdate) -> bool:
            cur_raw = self._read_current(u)
            if u.value in UNLIMITED:
                return True
            try:
                new = int(u.value)
            except ValueError:
                # cpuset-style list: growing set = increase
                try:
                    from koordinator_tpu.koordlet.system.procfs import parse_cpu_list

                    new_set = set(parse_cpu_list(u.value))
                    cur_set = (
                        set(parse_cpu_list(cur_raw)) if cur_raw is not None else set()
                    )
                    return new_set >= cur_set
                except ValueError:
                    return False
            if cur_raw is None or cur_raw in UNLIMITED:
                return cur_raw is None
            try:
                return new >= int(cur_raw)
            except ValueError:
                return True

        increases: list[ResourceUpdate] = []
        decreases: list[ResourceUpdate] = []
        merges: list[ResourceUpdate] = []
        for u in updates:
            if u.resource.name == "cpuset.cpus":
                # Sideways cpuset moves (e.g. '0-3' -> '4-7') fail in both
                # orders; write the union first parent-first (merge), then
                # the final value child-first (shrink) — the reference's
                # merge-then-shrink discipline.
                try:
                    from koordinator_tpu.koordlet.system.procfs import (
                        format_cpu_list, parse_cpu_list,
                    )

                    cur_raw = self._read_current(u)
                    new_set = set(parse_cpu_list(u.value))
                    cur_set = set(parse_cpu_list(cur_raw)) if cur_raw else set()
                    if not (new_set >= cur_set or new_set <= cur_set):
                        merges.append(dataclasses.replace(
                            u, value=format_cpu_list(sorted(new_set | cur_set))
                        ))
                    (increases if new_set >= cur_set else decreases).append(u)
                    continue
                except ValueError:
                    pass
            (increases if is_increase(u) else decreases).append(u)

        ordered = (
            sorted(merges, key=lambda u: u.depth)
            + sorted(increases, key=lambda u: u.depth)
            + sorted(decreases, key=lambda u: -u.depth)
        )
        results: dict[int, UpdateResult] = {}
        for u in ordered:
            results[id(u)] = self.update(u)
        return [results[id(u)] for u in updates]

    def forget(self, rel_dir_prefix: str) -> None:
        """Drop cache entries under a removed cgroup dir."""
        with self._lock:
            for key in [k for k in self._cache if k[1].startswith(rel_dir_prefix)]:
                del self._cache[key]
