"""Runtime hooks: container-lifecycle QoS injection (reference:
``pkg/koordlet/runtimehooks/`` — NRI server ``nri/server.go:34``, hook
registry ``hooks/hooks.go:53``, cgroup reconciler ``reconciler/reconciler.go``,
plugins under ``hooks/*``).

Flow: the container runtime (NRI/proxy) raises lifecycle events; each event
builds a :class:`~.protocol.PodContext`/:class:`~.protocol.ContainerContext`;
registered hook plugins mutate the context's *response* (cgroup values, env
vars, cpuset); the server turns the response into an NRI adjustment or direct
cgroup writes through the resource executor. The :class:`~.reconciler.Reconciler`
re-applies the same rules periodically from informer state as a safety net.
"""

from koordinator_tpu.koordlet.runtimehooks.hooks import (
    HookRegistry, Stage,
)
from koordinator_tpu.koordlet.runtimehooks.protocol import (
    ContainerContext, PodContext,
)
