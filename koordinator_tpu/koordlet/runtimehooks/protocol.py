"""Hook protocol contexts (reference: ``runtimehooks/protocol/`` —
pod/container/kubeQOS context objects).

A context carries the *target* (what the runtime is about to create/update)
and accumulates the *response* (what koordinator wants changed). ``apply``
pushes the response to the kernel through the resource executor — the same
code path serves NRI adjustments and reconciler re-application.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from koordinator_tpu.koordlet.resourceexecutor import (
    ResourceUpdate, ResourceUpdateExecutor,
)
from koordinator_tpu.koordlet.statesinformer import ContainerMeta, PodMeta
from koordinator_tpu.koordlet.system import cgroup as cg
from koordinator_tpu.koordlet.system.config import SystemConfig


@dataclasses.dataclass
class Response:
    """Accumulated desired changes."""

    cgroup_values: dict[str, str] = dataclasses.field(default_factory=dict)
    env: dict[str, str] = dataclasses.field(default_factory=dict)
    cpuset_cpus: Optional[str] = None
    cpuset_mems: Optional[str] = None
    core_sched_group: Optional[str] = None  # group id; "" = opt out
    #: resctrl placement: ctrl-group name + optional schemata to program
    #: (applied by ResctrlUpdater, not the cgroup executor)
    resctrl_group: Optional[str] = None
    resctrl_schemata: Optional[str] = None

    def set_cgroup(self, resource: cg.CgroupResource, value: str) -> None:
        self.cgroup_values[resource.name] = value


@dataclasses.dataclass
class PodContext:
    pod: PodMeta
    cgroup_dir: str
    response: Response = dataclasses.field(default_factory=Response)

    @classmethod
    def from_pod(cls, pod: PodMeta, cfg: SystemConfig) -> "PodContext":
        return cls(pod=pod, cgroup_dir=pod.cgroup_dir(cfg))

    def apply(self, executor: ResourceUpdateExecutor) -> int:
        """Write the response's cgroup part; returns number of kernel writes."""
        return _apply_response(self.response, self.cgroup_dir, executor)


@dataclasses.dataclass
class ContainerContext:
    pod: PodMeta
    container: ContainerMeta
    cgroup_dir: str
    response: Response = dataclasses.field(default_factory=Response)

    @classmethod
    def from_container(cls, pod: PodMeta, container: ContainerMeta,
                       cfg: SystemConfig) -> "ContainerContext":
        rel = container.cgroup_dir or cfg.container_cgroup_dir(
            pod.kube_qos, pod.uid, container.container_id
        )
        return cls(pod=pod, container=container, cgroup_dir=rel)

    def apply(self, executor: ResourceUpdateExecutor) -> int:
        return _apply_response(self.response, self.cgroup_dir, executor)


def _apply_response(response: Response, rel_dir: str,
                    executor: ResourceUpdateExecutor) -> int:
    updates = []
    for name, value in response.cgroup_values.items():
        updates.append(ResourceUpdate(cg.resource_by_name(name), rel_dir, value))
    if response.cpuset_cpus is not None:
        updates.append(ResourceUpdate(cg.CPUSET_CPUS, rel_dir, response.cpuset_cpus))
    if response.cpuset_mems is not None:
        updates.append(ResourceUpdate(cg.CPUSET_MEMS, rel_dir, response.cpuset_mems))
    results = executor.leveled_update_batch(updates)
    return sum(1 for r in results if r.updated)
