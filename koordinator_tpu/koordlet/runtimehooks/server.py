"""Hook server: the koordlet's runtimehooks plugins served at the
runtime-proxy boundary, across a process boundary.

The reference splits this seam over two processes and a wire protocol:
koordlet's hook server (``runtimehooks/nri/server.go:34`` for NRI,
``runtimehooks/proxyserver/`` for the legacy proxy) answers lifecycle
hooks raised by koord-runtime-proxy (``runtimeproxy/dispatcher/
dispatcher.go``), which interposes the kubelet<->containerd CRI path.
This module is the same split for this framework's transport:

- :class:`RegistryHookServer` (koordlet process) adapts the plugin
  :class:`~koordinator_tpu.koordlet.runtimehooks.hooks.HookRegistry`
  to the proxy's ``HookServer.handle(hook, request)`` contract, so the
  whole plugin set (GroupIdentity, BatchResource, CPUSetAllocator, ...)
  serves remote hook dispatch.  Served over the wire by attaching a
  ``transport.services.HookService`` wrapping a ``Dispatcher`` that has
  this server registered.
- :class:`RemoteHookServer` (proxy process) is the other half: a local
  ``HookServer`` whose ``handle`` calls the koordlet's HookService over
  an ``RpcClient`` — fail-open on transport errors, matching
  dispatcher.go's contract that a dead hook server never blocks a CRI
  call.

Wire mapping (both directions ride HOOK_REQUEST/HOOK_RESPONSE frames,
the api.proto:148 shapes): ``HookRequest.resources`` carries the pod's
(extended) resource requests in canonical integer units — that is what
BatchResource et al derive kernel limits from; plugin ``Response``
cgroup values come back in ``resources`` keyed by cgroup file name, and
env injections in ``envs``.
"""

from __future__ import annotations

from typing import Optional

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.qos import QoSClass
from koordinator_tpu.koordlet.runtimehooks.hooks import HookRegistry, Stage
from koordinator_tpu.koordlet.runtimehooks.protocol import (
    ContainerContext,
    PodContext,
    Response,
)
from koordinator_tpu.koordlet.statesinformer import ContainerMeta, PodMeta
from koordinator_tpu.runtimeproxy import HookRequest, HookResponse, HookType

_KUBE_QOS_BY_CLASS = {
    QoSClass.BE: "besteffort",
    QoSClass.LS: "burstable",
    QoSClass.LSR: "guaranteed",
    QoSClass.LSE: "guaranteed",
}


def pod_meta_from_request(request: HookRequest) -> PodMeta:
    """Rebuild the agent's pod model from the CRI-call context."""
    labels = dict(request.labels)
    qos = QoSClass.parse(labels.get(ext.LABEL_POD_QOS, ""))
    meta = request.pod_meta
    return PodMeta(
        uid=meta.get("uid", ""),
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        qos_class=qos,
        kube_qos=meta.get("kube_qos",
                          _KUBE_QOS_BY_CLASS.get(qos, "besteffort")),
        priority=int(meta.get("priority", 0) or 0),
        requests={k: int(v) for k, v in request.resources.items()},
        annotations=dict(request.annotations),
        labels=labels,
    )


def response_to_hook_response(response: Response) -> HookResponse:
    """Plugin Response -> proxy-mergeable partial update."""
    resources = dict(response.cgroup_values)
    if response.cpuset_cpus is not None:
        resources["cpuset.cpus"] = response.cpuset_cpus
    if response.cpuset_mems is not None:
        resources["cpuset.mems"] = response.cpuset_mems
    annotations = {}
    if response.core_sched_group is not None:
        annotations[ext.DOMAIN + "/core-sched-group"] = (
            response.core_sched_group)
    if response.resctrl_group is not None:
        annotations[ext.DOMAIN + "/resctrl-group"] = (
            response.resctrl_group)
    return HookResponse(
        annotations=annotations,
        resources=resources,
        envs=dict(response.env),
    )


class RegistryHookServer:
    """koordlet-side ``HookServer``: run the registry's plugins for the
    hook's stage and return their accumulated response."""

    #: HookType.value == Stage.value for every lifecycle point, by
    #: construction (both mirror api.proto's hook names)
    def __init__(self, registry: HookRegistry):
        self.registry = registry

    def handle(self, hook: HookType,
               request: HookRequest) -> Optional[HookResponse]:
        stage = Stage(hook.value)
        pod = pod_meta_from_request(request)
        if request.container_meta:
            ctx = ContainerContext(
                pod=pod,
                container=ContainerMeta(
                    name=request.container_meta.get("name", ""),
                    container_id=request.container_meta.get("id", ""),
                ),
                cgroup_dir=request.cgroup_parent,
            )
        else:
            ctx = PodContext(pod=pod, cgroup_dir=request.cgroup_parent)
        self.registry.run(stage, ctx)
        return response_to_hook_response(ctx.response)


class RemoteHookServer:
    """Proxy-side ``HookServer`` over the framed transport: dispatch to
    the koordlet's HookService in its own process, fail-open."""

    def __init__(self, client):
        self.client = client

    def handle(self, hook: HookType,
               request: HookRequest) -> Optional[HookResponse]:
        from koordinator_tpu.transport.services import hook_remote

        out = hook_remote(self.client, hook, request, fail_open=True)
        if out is None:
            return None
        return HookResponse(
            labels=out.get("labels", {}),
            annotations=out.get("annotations", {}),
            cgroup_parent=out.get("cgroup_parent", ""),
            resources=out.get("resources", {}),
            envs=out.get("envs", {}),
        )
