"""Hook registry + stages (reference: ``runtimehooks/hooks/hooks.go`` —
``Register`` :53, ``RunHooks`` :92).

Plugins register (stage, name, fn); the server/reconciler runs every hook of
a stage over a context. Hook errors are collected, not fatal — a broken
plugin must not block container creation (the reference logs and continues).
"""

from __future__ import annotations

import enum
from typing import Callable, Iterable


class Stage(enum.Enum):
    PRE_RUN_POD_SANDBOX = "PreRunPodSandbox"
    PRE_CREATE_CONTAINER = "PreCreateContainer"
    PRE_START_CONTAINER = "PreStartContainer"
    POST_START_CONTAINER = "PostStartContainer"
    PRE_UPDATE_CONTAINER = "PreUpdateContainerResources"
    POST_STOP_POD_SANDBOX = "PostStopPodSandbox"


class HookRegistry:
    def __init__(self):
        self._hooks: dict[Stage, list[tuple[str, Callable]]] = {
            stage: [] for stage in Stage
        }

    def register(self, stage: Stage, name: str, fn: Callable) -> None:
        self._hooks[stage].append((name, fn))

    def hooks_of(self, stage: Stage) -> Iterable[tuple[str, Callable]]:
        return tuple(self._hooks[stage])

    def run(self, stage: Stage, ctx) -> list[tuple[str, Exception]]:
        """Run all hooks of a stage; returns (hook name, error) failures."""
        failures = []
        for name, fn in self._hooks[stage]:
            try:
                fn(ctx)
            except Exception as e:  # noqa: BLE001 - isolate plugin faults
                failures.append((name, e))
        return failures
