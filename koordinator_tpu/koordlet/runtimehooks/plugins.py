"""The hook plugins (reference: ``runtimehooks/hooks/*`` — one dir per hook;
gated by the RUNTIMEHOOK_GATES feature switches).

Each plugin is a callable over a Pod/ContainerContext that fills in the
response. Registration wires them into the registry at the stages the
reference uses (groupidentity at sandbox + container, cpuset/batchresource at
container create/update, gpu/rdma env at container create, coresched at
container start).
"""

from __future__ import annotations

from typing import Callable, Optional

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.crds import NodeSLO
from koordinator_tpu.api.qos import QoSClass
from koordinator_tpu.features import RUNTIMEHOOK_GATES
from koordinator_tpu.koordlet.runtimehooks.hooks import HookRegistry, Stage
from koordinator_tpu.koordlet.runtimehooks.protocol import (
    ContainerContext, PodContext,
)
from koordinator_tpu.koordlet.system import cgroup as cg
from koordinator_tpu.koordlet.system.coresched import CoreSched

CFS_PERIOD_US = 100_000


class GroupIdentity:
    """bvt_warp_ns by QoS class (hooks/groupidentity/bvt.go:29): the Anolis
    group-identity scheduler gives LS groups wakeup preemption over BE."""

    name = "GroupIdentity"

    def __init__(self, node_slo: Callable[[], NodeSLO]):
        self.node_slo = node_slo

    def bvt_of(self, qos: QoSClass) -> int:
        slo = self.node_slo()
        if qos.is_best_effort:
            return slo.resource_qos_be.cpu.group_identity
        if qos.is_latency_sensitive:
            return slo.resource_qos_ls.cpu.group_identity
        return 0

    def __call__(self, ctx: PodContext | ContainerContext) -> None:
        if not RUNTIMEHOOK_GATES.enabled(self.name):
            return
        ctx.response.set_cgroup(cg.CPU_BVT_WARP_NS, str(self.bvt_of(ctx.pod.qos_class)))


class CPUSetAllocator:
    """Apply the scheduler's cpuset decision from the resource-status
    annotation (hooks/cpuset/) — LSR/LSE pods get their exclusive CPUs,
    LS pods get the share pool."""

    name = "CPUSetAllocator"

    def __init__(self, share_pool: Optional[Callable[[], str]] = None):
        #: cpus for LS pods (the non-exclusive share pool), injected
        self.share_pool = share_pool

    def __call__(self, ctx: PodContext | ContainerContext) -> None:
        if not RUNTIMEHOOK_GATES.enabled(self.name):
            return
        status = ext.get_resource_status(ctx.pod.annotations)
        cpuset = status.get("cpuset", "")
        if cpuset:
            ctx.response.cpuset_cpus = cpuset
        elif (
            ctx.pod.qos_class is QoSClass.LS
            and self.share_pool is not None
        ):
            pool = self.share_pool()
            if pool:
                ctx.response.cpuset_cpus = pool


class BatchResource:
    """cfs quota + memory limit from batch-cpu/batch-memory requests
    (hooks/batchresource/): BE pods request extended batch resources; the
    kernel limits must be derived from them since kubelet sees only
    zero-valued native requests."""

    name = "BatchResource"

    def __call__(self, ctx: PodContext | ContainerContext) -> None:
        if not RUNTIMEHOOK_GATES.enabled(self.name):
            return
        if not ctx.pod.qos_class.is_best_effort:
            return
        batch_cpu = int(ctx.pod.requests.get(ext.RESOURCE_BATCH_CPU, 0))
        batch_mem = int(ctx.pod.requests.get(ext.RESOURCE_BATCH_MEMORY, 0))
        if batch_cpu > 0:
            quota = batch_cpu * CFS_PERIOD_US // 1000
            ctx.response.set_cgroup(cg.CPU_CFS_QUOTA, str(quota))
            ctx.response.set_cgroup(
                cg.CPU_SHARES, str(max(2, batch_cpu * 1024 // 1000))
            )
        if batch_mem > 0:
            ctx.response.set_cgroup(cg.MEMORY_LIMIT, str(batch_mem))


class GPUEnvInject:
    """NVIDIA/HAMi-style env injection from the device-allocated annotation
    (hooks/gpu/): the scheduler's device minors become the container's
    visible-devices env."""

    name = "GPUEnvInject"

    def __call__(self, ctx: ContainerContext) -> None:
        if not RUNTIMEHOOK_GATES.enabled(self.name):
            return
        allocations = ext.get_device_allocations(ctx.pod.annotations)
        gpus = allocations.get("gpu", [])
        if not gpus:
            return
        minors = ",".join(str(g.get("minor", 0)) for g in gpus)
        ctx.response.env["NVIDIA_VISIBLE_DEVICES"] = minors
        first = gpus[0].get("resources", {})
        ratio = first.get(ext.RESOURCE_GPU_MEMORY_RATIO, 100)
        if ratio < 100:  # shared GPU: expose the memory cap
            mem = first.get(ext.RESOURCE_GPU_MEMORY, 0)
            if mem:
                ctx.response.env["CUDA_MEM_LIMIT"] = str(mem)


class RDMADeviceInject:
    """RDMA VF device env/mount inject (hooks/rdma/)."""

    name = "RDMADeviceInject"

    def __call__(self, ctx: ContainerContext) -> None:
        if not RUNTIMEHOOK_GATES.enabled(self.name):
            return
        allocations = ext.get_device_allocations(ctx.pod.annotations)
        rdma = allocations.get("rdma", [])
        if rdma:
            ctx.response.env["RDMA_DEVICES"] = ",".join(
                str(r.get("minor", 0)) for r in rdma
            )


class CoreSchedHook:
    """Core-scheduling cookies per pod group (hooks/coresched/): pods of the
    same group share SMT siblings; BE pods never share with LS."""

    name = "CoreSched"

    def __init__(self, node_slo: Callable[[], NodeSLO],
                 core_sched: Optional[CoreSched] = None):
        self.node_slo = node_slo
        self.core_sched = core_sched

    def __call__(self, ctx: PodContext | ContainerContext) -> None:
        if not RUNTIMEHOOK_GATES.enabled(self.name):
            return
        slo = self.node_slo()
        qos = ctx.pod.qos_class
        enable = (
            slo.resource_qos_be.cpu.core_sched
            if qos.is_best_effort
            else slo.resource_qos_ls.cpu.core_sched
        )
        if enable:
            # group id: QoS class + pod uid — each pod is its own core-sched
            # group (the reference's default pod-level policy)
            ctx.response.core_sched_group = f"{qos.name}/{ctx.pod.uid}"


class CPUNormalization:
    """Scale LS cfs quota by the node's CPU-model normalization ratio
    (hooks/cpunormalization/): on fast CPU models a pod's quota shrinks so a
    'core' means the same work everywhere."""

    name = "CPUNormalization"

    def __init__(self, ratio_pct: Callable[[], int]):
        self.ratio_pct = ratio_pct

    def __call__(self, ctx: ContainerContext) -> None:
        if not RUNTIMEHOOK_GATES.enabled(self.name):
            return
        if ctx.pod.qos_class is not QoSClass.LS:
            return
        ratio = self.ratio_pct()
        if ratio == 100:
            return
        limit_milli = int(ctx.pod.limits.get("cpu", 0))
        if limit_milli <= 0:
            return
        quota = limit_milli * CFS_PERIOD_US // 1000 * 100 // ratio
        ctx.response.set_cgroup(cg.CPU_CFS_QUOTA, str(quota))


class ResctrlHook:
    """Per-pod resctrl placement (hooks/resctrl/): a pod carrying the
    resctrl annotation ({"l3": pct, "mb": pct}) gets its own ctrl group with
    the requested L3 way mask / MBA throttle; pods without it fall into the
    per-QoS groups the qosmanager resctrl plugin maintains.  The response's
    resctrl fields are applied by :class:`ResctrlUpdater` (updater.go
    equivalent) — resctrl is not a cgroup, so it bypasses the executor."""

    name = "Resctrl"

    def __init__(self, num_ways: int = 20):
        self.num_ways = num_ways

    def __call__(self, ctx: PodContext | ContainerContext) -> None:
        if not RUNTIMEHOOK_GATES.enabled(self.name):
            return
        import json

        from koordinator_tpu.koordlet.system import resctrl as rc

        raw = ctx.pod.annotations.get(ext.ANNOTATION_RESCTRL, "")
        if raw:
            try:
                spec = json.loads(raw)
            except ValueError:
                return
            ctx.response.resctrl_group = f"koord-pod-{ctx.pod.uid}"
            lines = []
            l3 = int(spec.get("l3", 100))
            mask = rc.percent_to_way_mask(l3, self.num_ways)
            lines.append(f"L3:0={mask:x}")
            if "mb" in spec:
                lines.append(f"MB:0={int(spec['mb'])}")
            ctx.response.resctrl_schemata = "\n".join(lines) + "\n"
        else:
            # QoS-class group membership (LSE/LSR -> LSR, LS -> LS, BE -> BE)
            qos = ctx.pod.qos_class
            group = (
                rc.GROUP_BE if qos.is_best_effort
                else rc.GROUP_LSR if qos.name in ("LSE", "LSR")
                else rc.GROUP_LS
            )
            ctx.response.resctrl_group = group


class ResctrlUpdater:
    """Applies a hook response's resctrl fields to the resctrl fs: ensures
    the group, programs schemata, binds the pod's tasks."""

    def __init__(self, cfg=None):
        from koordinator_tpu.koordlet.system.resctrl import ResctrlFS

        self.fs = ResctrlFS(cfg)

    def apply(self, response, pids: list[int]) -> None:
        if response.resctrl_group is None:
            return
        self.fs.ensure_group(response.resctrl_group)
        if response.resctrl_schemata is not None:
            import os

            path = os.path.join(
                self.fs.group_dir(response.resctrl_group), "schemata"
            )
            with open(path, "w") as f:
                f.write(response.resctrl_schemata)
        if pids:
            self.fs.add_tasks(response.resctrl_group, pids)

    def remove_group(self, pod_uid: str) -> None:
        """Pod removal: drop the per-pod ctrl group (RemovePodResctrlResources)."""
        import os
        import shutil

        path = self.fs.group_dir(f"koord-pod-{pod_uid}")
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)


#: tc class handles per QoS tier (netqos_tc.go scheme: one htb class per
#: tier under the root qdisc; high = prod, mid = mid, low = BE)
TC_CLASSID_HIGH = 0x1_0002
TC_CLASSID_MID = 0x1_0003
TC_CLASSID_LOW = 0x1_0004


class TCNetworkQoS:
    """tc network QoS (hooks/tc/): classify each pod's traffic into the
    per-tier htb class via net_cls.classid; the qdisc/class setup itself is
    rendered by :func:`tc_setup_commands` for the node agent to install."""

    name = "TCNetworkQoS"

    def __call__(self, ctx: PodContext | ContainerContext) -> None:
        if not RUNTIMEHOOK_GATES.enabled(self.name):
            return
        qos = ctx.pod.qos_class
        classid = (
            TC_CLASSID_LOW if qos.is_best_effort
            else TC_CLASSID_HIGH if qos.is_latency_sensitive
            else TC_CLASSID_MID
        )
        ctx.response.set_cgroup(cg.NET_CLS_CLASSID, str(classid))


def tc_setup_commands(
    iface: str, total_mbps: int,
    high_pct: int = 40, mid_pct: int = 30, low_pct: int = 30,
) -> list[list[str]]:
    """The tc qdisc/class plan (helper.go): an htb root with one class per
    tier — guaranteed rate by percentage, ceil at line rate so idle bandwidth
    is borrowable.  Returned as argv lists for the agent to execute."""
    def rate(pct: int) -> str:
        return f"{total_mbps * pct // 100}mbit"

    line = f"{total_mbps}mbit"
    return [
        ["tc", "qdisc", "add", "dev", iface, "root", "handle", "1:", "htb",
         "default", "2"],
        ["tc", "class", "add", "dev", iface, "parent", "1:", "classid", "1:2",
         "htb", "rate", rate(high_pct), "ceil", line],
        ["tc", "class", "add", "dev", iface, "parent", "1:", "classid", "1:3",
         "htb", "rate", rate(mid_pct), "ceil", line],
        ["tc", "class", "add", "dev", iface, "parent", "1:", "classid", "1:4",
         "htb", "rate", rate(low_pct), "ceil", line],
    ]


class TerwayQoS:
    """terway dataplane bandwidth limits (hooks/terwayqos/): each pod's
    ingress/egress bps from the networkQOS annotation is written as a JSON
    file the terway daemon watches (``<var_run_root>/terway-qos/<uid>.json``);
    removal deletes the file."""

    name = "TerwayQoS"

    def __init__(self, cfg=None):
        from koordinator_tpu.koordlet.system.config import get_config

        self.cfg = cfg or get_config()

    @property
    def root(self) -> str:
        import os

        return os.path.join(self.cfg.var_run_root, "terway-qos")

    def __call__(self, ctx: PodContext | ContainerContext) -> None:
        if not RUNTIMEHOOK_GATES.enabled(self.name):
            return
        import json
        import os

        raw = ctx.pod.annotations.get(ext.ANNOTATION_NETWORK_QOS, "")
        if not raw:
            return
        try:
            spec = json.loads(raw)
        except ValueError:
            return
        os.makedirs(self.root, exist_ok=True)
        out = {
            "podUID": ctx.pod.uid,
            "ingressBps": int(spec.get("ingressBps", 0)),
            "egressBps": int(spec.get("egressBps", 0)),
            "prio": 2 if ctx.pod.qos_class.is_best_effort else 0,
        }
        with open(os.path.join(self.root, f"{ctx.pod.uid}.json"), "w") as f:
            json.dump(out, f)

    def remove(self, pod_uid: str) -> None:
        import os

        try:
            os.unlink(os.path.join(self.root, f"{pod_uid}.json"))
        except OSError:
            pass


def register_default_hooks(
    registry: HookRegistry,
    node_slo: Callable[[], NodeSLO],
    share_pool: Optional[Callable[[], str]] = None,
    cpu_normalization_ratio: Optional[Callable[[], int]] = None,
    core_sched: Optional[CoreSched] = None,
) -> dict[str, object]:
    """Wire the default plugin set at the reference's stages."""
    group_identity = GroupIdentity(node_slo)
    cpuset = CPUSetAllocator(share_pool)
    batch = BatchResource()
    gpu = GPUEnvInject()
    rdma = RDMADeviceInject()
    coresched = CoreSchedHook(node_slo, core_sched)
    cpunorm = CPUNormalization(cpu_normalization_ratio or (lambda: 100))
    resctrl = ResctrlHook()
    tc = TCNetworkQoS()
    terway = TerwayQoS()

    registry.register(Stage.PRE_RUN_POD_SANDBOX, group_identity.name, group_identity)
    registry.register(Stage.PRE_RUN_POD_SANDBOX, resctrl.name, resctrl)
    registry.register(Stage.PRE_RUN_POD_SANDBOX, tc.name, tc)
    registry.register(Stage.PRE_RUN_POD_SANDBOX, terway.name, terway)
    for stage in (Stage.PRE_CREATE_CONTAINER, Stage.PRE_UPDATE_CONTAINER):
        registry.register(stage, group_identity.name, group_identity)
        registry.register(stage, cpuset.name, cpuset)
        registry.register(stage, batch.name, batch)
        registry.register(stage, cpunorm.name, cpunorm)
    registry.register(Stage.PRE_CREATE_CONTAINER, gpu.name, gpu)
    registry.register(Stage.PRE_CREATE_CONTAINER, rdma.name, rdma)
    registry.register(Stage.PRE_CREATE_CONTAINER, resctrl.name, resctrl)
    registry.register(Stage.PRE_CREATE_CONTAINER, tc.name, tc)
    registry.register(Stage.PRE_START_CONTAINER, coresched.name, coresched)
    return {
        "groupidentity": group_identity,
        "cpuset": cpuset,
        "batchresource": batch,
        "gpu": gpu,
        "rdma": rdma,
        "coresched": coresched,
        "cpunormalization": cpunorm,
        "resctrl": resctrl,
        "tc": tc,
        "terwayqos": terway,
    }
