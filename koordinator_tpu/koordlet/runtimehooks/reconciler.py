"""Cgroup reconciler: the async safety net (reference:
``runtimehooks/reconciler/reconciler.go`` — ``reconcilePodCgroup`` :433,
``doKubeQOSCgroup`` :407).

Runtime events can be missed (agent restart, NRI race); the reconciler
periodically rebuilds hook contexts from informer state and re-applies them.
The executor's last-value cache makes this idempotent and cheap.
"""

from __future__ import annotations

import os

from koordinator_tpu.koordlet.resourceexecutor import ResourceUpdateExecutor
from koordinator_tpu.koordlet.runtimehooks.hooks import HookRegistry, Stage
from koordinator_tpu.koordlet.runtimehooks.protocol import (
    ContainerContext, PodContext,
)
from koordinator_tpu.koordlet.statesinformer import StatesInformer
from koordinator_tpu.koordlet.system.config import SystemConfig


class Reconciler:
    def __init__(self, states: StatesInformer, registry: HookRegistry,
                 executor: ResourceUpdateExecutor, cfg: SystemConfig,
                 resctrl_updater=None):
        self.states = states
        self.registry = registry
        self.executor = executor
        self.cfg = cfg
        #: applies hook responses' resctrl fields (ctrl group + schemata);
        #: resctrl is not a cgroup, so it bypasses the executor.  Only
        #: per-pod (koord-pod-*) groups are reconciled here — the per-QoS
        #: tier groups are the qosmanager resctrl plugin's job.
        self.resctrl_updater = resctrl_updater
        #: last applied (schemata, pids) per pod — keeps quiet passes
        #: write-free for resctrl too (the executor cache analog)
        self._resctrl_applied: dict[str, tuple] = {}
        #: pod uid -> trace annotation already joined: the reconcile
        #: span marks the pod's FIRST reconcile under a given trace
        #: (the enqueue-to-cgroup endpoint), not every periodic tick of
        #: the pod's lifetime — unbounded re-spans would churn the
        #: debug ring and grow a JSONL export forever
        self._trace_joined: dict[str, str] = {}

    def reconcile_once(self) -> int:
        """Re-apply pod + container rules from current state; returns the
        number of kernel writes actually performed.

        A pod carrying a trace-context annotation (stamped by the
        scheduler at bind and carried onto the pod object by the
        deployment shell) reconciles inside a ``koordlet.reconcile_pod``
        span joined to that trace — the last hop of the pod's
        enqueue-to-cgroup timeline."""
        from koordinator_tpu import tracing

        writes = 0
        live: set[str] = set()
        seen_uids: set[str] = set()
        for pod in self.states.get_all_pods():
            if not pod.is_running:
                continue
            seen_uids.add(pod.uid)
            annotation = (pod.annotations or {}).get(
                tracing.TRACE_ANNOTATION)
            trace_ctx = tracing.TraceContext.from_annotation(annotation)
            if (trace_ctx is None
                    or self._trace_joined.get(pod.uid) == annotation):
                writes += self._reconcile_pod(pod, live)
                continue
            self._trace_joined[pod.uid] = annotation
            with tracing.TRACER.span(
                    "koordlet.reconcile_pod", service="koordlet",
                    parent=trace_ctx,
                    attributes={"pod": pod.name, "uid": pod.uid}) as sp:
                pod_writes = self._reconcile_pod(pod, live)
                sp.set_attribute("writes", pod_writes)
            writes += pod_writes
        # joined-trace registry follows pod lifetime (a reused uid with
        # a NEW trace annotation re-joins)
        for uid in list(self._trace_joined):
            if uid not in seen_uids:
                del self._trace_joined[uid]
        if self.resctrl_updater is not None and getattr(
                self.states, "pods_synced", True):
            # RemovePodResctrlResources: enumerate on-disk koord-pod-*
            # groups (not an in-memory set — it would leak groups of pods
            # that left while the agent was down) and drop the dead ones.
            # Gated on the informer having synced once: a transiently-empty
            # pod list (first tick after restart) must not strip every
            # running pod's L3/MB isolation.
            root = self.resctrl_updater.fs.root
            try:
                existing = [d for d in os.listdir(root)
                            if d.startswith("koord-pod-")]
            except OSError:
                existing = []
            for d in existing:
                uid = d[len("koord-pod-"):]
                if uid not in live:
                    self.resctrl_updater.remove_group(uid)
                    self._resctrl_applied.pop(uid, None)
        return writes

    def _reconcile_pod(self, pod, live: set[str]) -> int:
        """One pod's hook re-application (the loop body of
        reconcile_once); returns this pod's kernel writes."""
        writes = 0
        pod_ctx = PodContext.from_pod(pod, self.cfg)
        self.registry.run(Stage.PRE_RUN_POD_SANDBOX, pod_ctx)
        self.registry.run(Stage.PRE_UPDATE_CONTAINER, pod_ctx)
        writes += pod_ctx.apply(self.executor)
        self._reconcile_resctrl(pod, pod_ctx, live)
        for container in pod.containers:
            ctx = ContainerContext.from_container(pod, container, self.cfg)
            self.registry.run(Stage.PRE_CREATE_CONTAINER, ctx)
            writes += ctx.apply(self.executor)
        return writes

    def _reconcile_resctrl(self, pod, pod_ctx, live: set[str]) -> None:
        group = pod_ctx.response.resctrl_group
        if (self.resctrl_updater is None or group is None
                or not group.startswith("koord-pod-")):
            return
        live.add(pod.uid)
        pids = list(pod.pids or ()) or self._pod_pids(pod)
        key = (group, pod_ctx.response.resctrl_schemata,
               tuple(sorted(pids)))
        if self._resctrl_applied.get(pod.uid) == key and os.path.isdir(
                self.resctrl_updater.fs.group_dir(group)):
            return   # unchanged: write-free pass
        try:
            self.resctrl_updater.apply(pod_ctx.response, pids=pids)
            self._resctrl_applied[pod.uid] = key
        except OSError:
            # hardware-rejected schemata / unmounted resctrl must not
            # abort reconciliation of the remaining pods
            pass

    def _pod_pids(self, pod) -> list[int]:
        """Task ids from the pod cgroup's cgroup.procs (the informer may
        not carry pids; resctrl binding needs them node-side anyway)."""
        path = self.cfg.cgroup_abs_path(
            "cpu", pod.cgroup_dir(self.cfg), "cgroup.procs")
        try:
            with open(path) as f:
                return [int(x) for x in f.read().split()
                        if x.strip().isdigit()]
        except OSError:
            return []
