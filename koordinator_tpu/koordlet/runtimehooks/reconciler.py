"""Cgroup reconciler: the async safety net (reference:
``runtimehooks/reconciler/reconciler.go`` — ``reconcilePodCgroup`` :433,
``doKubeQOSCgroup`` :407).

Runtime events can be missed (agent restart, NRI race); the reconciler
periodically rebuilds hook contexts from informer state and re-applies them.
The executor's last-value cache makes this idempotent and cheap.
"""

from __future__ import annotations

from koordinator_tpu.koordlet.resourceexecutor import ResourceUpdateExecutor
from koordinator_tpu.koordlet.runtimehooks.hooks import HookRegistry, Stage
from koordinator_tpu.koordlet.runtimehooks.protocol import (
    ContainerContext, PodContext,
)
from koordinator_tpu.koordlet.statesinformer import StatesInformer
from koordinator_tpu.koordlet.system.config import SystemConfig


class Reconciler:
    def __init__(self, states: StatesInformer, registry: HookRegistry,
                 executor: ResourceUpdateExecutor, cfg: SystemConfig):
        self.states = states
        self.registry = registry
        self.executor = executor
        self.cfg = cfg

    def reconcile_once(self) -> int:
        """Re-apply pod + container rules from current state; returns the
        number of kernel writes actually performed."""
        writes = 0
        for pod in self.states.get_all_pods():
            if not pod.is_running:
                continue
            pod_ctx = PodContext.from_pod(pod, self.cfg)
            self.registry.run(Stage.PRE_RUN_POD_SANDBOX, pod_ctx)
            self.registry.run(Stage.PRE_UPDATE_CONTAINER, pod_ctx)
            writes += pod_ctx.apply(self.executor)
            for container in pod.containers:
                ctx = ContainerContext.from_container(pod, container, self.cfg)
                self.registry.run(Stage.PRE_CREATE_CONTAINER, ctx)
                writes += ctx.apply(self.executor)
        return writes
