"""Metrics advisor: the collector framework + collectors (reference:
``pkg/koordlet/metricsadvisor/`` — registry ``plugins_profile.go:41-63``,
collectors under ``collectors/`` and ``devices/``).

Each collector implements :class:`Collector` and is driven by the framework's
``collect_once`` (tests) or the periodic runner in ``daemon``. Rate-style
metrics (CPU usage cores) keep per-target last-sample state inside the
collector, mirroring the reference's tick-delta approach.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Optional, Protocol

from koordinator_tpu.koordlet import metriccache as mc
from koordinator_tpu.koordlet.statesinformer import StatesInformer
from koordinator_tpu.koordlet.system import cgroup as cg
from koordinator_tpu.koordlet.system import procfs, psi
from koordinator_tpu.koordlet.system.config import SystemConfig, get_config


class Collector(Protocol):
    name: str

    def enabled(self) -> bool: ...

    def collect(self) -> None: ...


@dataclasses.dataclass
class _CPUTick:
    ts: float
    value: int  # cumulative jiffies or cumulative ns


class _Deps:
    def __init__(self, states: StatesInformer, cache: mc.MetricCache,
                 cfg: Optional[SystemConfig], clock):
        self.states = states
        self.cache = cache
        self.cfg = cfg or get_config()
        self.clock = clock


class NodeResourceCollector:
    """Node CPU (cores) + memory (bytes) usage (collectors/noderesource)."""

    name = "noderesource"

    def __init__(self, deps: _Deps):
        self.d = deps
        self._last: Optional[_CPUTick] = None
        self._last_percpu: dict[int, _CPUTick] = {}

    def enabled(self) -> bool:
        return os.path.exists(self.d.cfg.proc_path("stat"))

    def collect(self) -> None:
        from koordinator_tpu.features import KOORDLET_GATES

        now = self.d.clock()
        with open(self.d.cfg.proc_path("stat")) as f:
            raw = f.read()
        stat = procfs.parse_proc_stat(raw)
        if self._last is not None and now > self._last.ts:
            dt = now - self._last.ts
            cores = (stat.used_jiffies - self._last.value) / (
                procfs.JIFFIES_PER_SEC * dt
            )
            self.d.cache.append(mc.NODE_CPU_USAGE, max(0.0, cores), ts=now)
        self._last = _CPUTick(now, stat.used_jiffies)

        if KOORDLET_GATES.enabled("PerCPUMetric"):
            # per-core utilization series (PerCPUMetric): same delta step
            # per "cpuN" row, labeled by core index
            for cpu, row in procfs.parse_proc_stat_percpu(raw).items():
                last = self._last_percpu.get(cpu)
                if last is not None and now > last.ts:
                    dt = now - last.ts
                    cores = (row.used_jiffies - last.value) / (
                        procfs.JIFFIES_PER_SEC * dt
                    )
                    self.d.cache.append(
                        mc.NODE_PERCPU_USAGE, max(0.0, cores),
                        labels={"cpu": str(cpu)}, ts=now)
                self._last_percpu[cpu] = _CPUTick(now, row.used_jiffies)

        mem = procfs.read_meminfo(self.d.cfg)
        self.d.cache.append(mc.NODE_MEMORY_USAGE, float(mem.used_no_cache), ts=now)
        self.d.cache.append(
            mc.PAGE_CACHE_BYTES, float(mem.cached), ts=now
        )


class _CgroupCPUTracker:
    """Shared tick-delta logic over cpuacct.usage (v1, ns) / cpu.stat (v2, us)."""

    def __init__(self, cfg: SystemConfig):
        self.cfg = cfg
        self._last: dict[str, _CPUTick] = {}

    def usage_cores(self, key: str, rel_dir: str, now: float) -> Optional[float]:
        try:
            if self.cfg.use_cgroup_v2:
                raw = cg.cgroup_read(cg.CPU_STAT, rel_dir, self.cfg)
            else:
                raw = cg.cgroup_read(cg.CPUACCT_USAGE, rel_dir, self.cfg)
        except OSError:
            return None
        return self.usage_cores_from_raw(key, raw, now)

    def usage_cores_from_raw(self, key: str, raw: Optional[str],
                             now: float) -> Optional[float]:
        """Delta step over already-read file content (native batch path)."""
        if raw is None:
            return None
        try:
            if self.cfg.use_cgroup_v2:
                cum_ns = cg.parse_stat(raw).get("usage_usec", 0) * 1000
            else:
                cum_ns = int(raw.strip())
        except ValueError:
            return None
        last = self._last.get(key)
        self._last[key] = _CPUTick(now, cum_ns)
        if last is None or now <= last.ts:
            return None
        return max(0.0, (cum_ns - last.value) / 1e9 / (now - last.ts))

    def forget_missing(self, live_keys: set[str]) -> None:
        for key in [k for k in self._last if k not in live_keys]:
            del self._last[key]


class PodResourceCollector:
    """Per-pod/container CPU + memory from pod cgroup dirs
    (collectors/podresource)."""

    name = "podresource"

    def __init__(self, deps: _Deps):
        self.d = deps
        self._cpu = _CgroupCPUTracker(deps.cfg)
        #: (targets tuple) -> native.BatchReader, rebuilt on pod churn
        self._reader_key: tuple = ()
        self._reader = None

    def enabled(self) -> bool:
        return True

    def _targets(self) -> list[tuple[str, dict, str, str]]:
        """(key, labels, kind, abs path) for every file of every pod tick."""
        cfg = self.d.cfg
        cpu_res = cg.CPU_STAT if cfg.use_cgroup_v2 else cg.CPUACCT_USAGE
        rows = []
        for pod in self.d.states.get_all_pods():
            if not pod.is_running:
                continue
            rel = pod.cgroup_dir(cfg)
            labels = {"pod_uid": pod.uid}
            rows.append((pod.uid, labels, "cpu", cg.resource_path(cpu_res, rel, cfg)))
            rows.append((pod.uid, labels, "mem",
                         cg.resource_path(cg.MEMORY_USAGE, rel, cfg)))
            for container in pod.containers:
                ckey = f"{pod.uid}/{container.container_id}"
                crel = container.cgroup_dir or cfg.container_cgroup_dir(
                    pod.kube_qos, pod.uid, container.container_id
                )
                clabels = {"pod_uid": pod.uid,
                           "container_id": container.container_id}
                rows.append((ckey, clabels, "cpu",
                             cg.resource_path(cpu_res, crel, cfg)))
                rows.append((ckey, clabels, "mem",
                             cg.resource_path(cg.MEMORY_USAGE, crel, cfg)))
        return rows

    def collect(self) -> None:
        from koordinator_tpu import native

        now = self.d.clock()
        targets = self._targets()
        key = tuple(t[3] for t in targets)
        if key != self._reader_key:
            self._reader = native.BatchReader(list(key))
            self._reader_key = key
        contents = self._reader.read() if targets else []

        live: set[str] = set()
        for (tkey, labels, kind, _), raw in zip(targets, contents):
            live.add(tkey)
            is_container = "container_id" in labels
            if kind == "cpu":
                cores = self._cpu.usage_cores_from_raw(tkey, raw, now)
                if cores is not None:
                    metric = (
                        mc.CONTAINER_CPU_USAGE if is_container else mc.POD_CPU_USAGE
                    )
                    self.d.cache.append(metric, cores, labels, ts=now)
            elif raw is not None:
                try:
                    mem = float(raw.strip())
                except ValueError:
                    continue
                metric = (
                    mc.CONTAINER_MEMORY_USAGE if is_container
                    else mc.POD_MEMORY_USAGE
                )
                self.d.cache.append(metric, mem, labels, ts=now)
        self._cpu.forget_missing(live)


class BEResourceCollector:
    """Aggregate BestEffort-tier usage (collectors/beresource) — feeds the
    cpusuppress/cpuevict loops."""

    name = "beresource"

    def __init__(self, deps: _Deps):
        self.d = deps
        self._cpu = _CgroupCPUTracker(deps.cfg)

    def enabled(self) -> bool:
        return True

    def collect(self) -> None:
        now = self.d.clock()
        rel = self.d.cfg.kube_qos_dir("besteffort")
        cores = self._cpu.usage_cores("besteffort", rel, now)
        if cores is not None:
            self.d.cache.append(mc.BE_CPU_USAGE, cores, ts=now)


class SysResourceCollector:
    """system usage = node usage - sum(pod usage) (collectors/sysresource)."""

    name = "sysresource"

    def __init__(self, deps: _Deps):
        self.d = deps

    def enabled(self) -> bool:
        return True

    def collect(self) -> None:
        now = self.d.clock()
        window = 60.0
        node_cpu = self.d.cache.query(mc.NODE_CPU_USAGE, None, now - window, now)
        node_mem = self.d.cache.query(mc.NODE_MEMORY_USAGE, None, now - window, now)
        if node_cpu.empty and node_mem.empty:
            return
        pods_cpu = pods_mem = 0.0
        for pod in self.d.states.get_all_pods():
            labels = {"pod_uid": pod.uid}
            pods_cpu += self.d.cache.query(
                mc.POD_CPU_USAGE, labels, now - window, now
            ).latest()
            pods_mem += self.d.cache.query(
                mc.POD_MEMORY_USAGE, labels, now - window, now
            ).latest()
        self.d.cache.append(
            mc.SYS_CPU_USAGE, max(0.0, node_cpu.latest() - pods_cpu), ts=now
        )
        self.d.cache.append(
            mc.SYS_MEMORY_USAGE, max(0.0, node_mem.latest() - pods_mem), ts=now
        )


class PodThrottledCollector:
    """Per-container CFS throttle ratio from cpu.stat (collectors/podthrottled)."""

    name = "podthrottled"

    def __init__(self, deps: _Deps):
        self.d = deps
        self._last: dict[str, tuple[int, int]] = {}  # key -> (periods, throttled)

    def enabled(self) -> bool:
        return True

    def collect(self) -> None:
        now = self.d.clock()
        live: set[str] = set()
        for pod in self.d.states.get_all_pods():
            if not pod.is_running:
                continue
            rel = pod.cgroup_dir(self.d.cfg)
            try:
                stat = cg.parse_stat(cg.cgroup_read(cg.CPU_STAT, rel, self.d.cfg))
            except OSError:
                continue
            live.add(pod.uid)
            periods = stat.get("nr_periods", 0)
            throttled = stat.get("nr_throttled", 0)
            last = self._last.get(pod.uid)
            self._last[pod.uid] = (periods, throttled)
            if last is None:
                continue
            dp, dth = periods - last[0], throttled - last[1]
            if dp > 0:
                self.d.cache.append(
                    mc.CONTAINER_CPU_THROTTLED, dth / dp,
                    {"pod_uid": pod.uid}, ts=now,
                )
        for key in [k for k in self._last if k not in live]:
            del self._last[key]


class PSICollector:
    """Node + per-pod pressure stall averages (collectors/performance PSI)."""

    name = "psi"

    def __init__(self, deps: _Deps):
        self.d = deps

    def enabled(self) -> bool:
        from koordinator_tpu.features import KOORDLET_GATES

        return KOORDLET_GATES.enabled("PSICollector") and os.path.exists(
            cg.resource_path(cg.CPU_PRESSURE, "", self.d.cfg)
        )

    def collect(self) -> None:
        from koordinator_tpu import metrics

        now = self.d.clock()
        stats = psi.read_psi("", self.d.cfg)
        metrics.psi_cpu_some_avg10.set(stats.cpu.some.avg10)
        self.d.cache.append(mc.PSI_CPU_SOME_AVG10, stats.cpu.some.avg10, ts=now)
        self.d.cache.append(mc.PSI_MEM_FULL_AVG10, stats.mem.full.avg10, ts=now)
        self.d.cache.append(mc.PSI_IO_FULL_AVG10, stats.io.full.avg10, ts=now)


class ColdMemoryCollector:
    """kidled cold-page bytes per pod + node (collectors/coldmemoryresource)."""

    name = "coldmemory"

    def __init__(self, deps: _Deps):
        self.d = deps

    def enabled(self) -> bool:
        from koordinator_tpu.features import KOORDLET_GATES

        return KOORDLET_GATES.enabled("ColdPageCollector") and procfs.kidled_supported(
            self.d.cfg
        )

    def collect(self) -> None:
        now = self.d.clock()
        total = 0
        for pod in self.d.states.get_all_pods():
            rel = pod.cgroup_dir(self.d.cfg)
            try:
                raw = cg.cgroup_read(cg.MEMORY_IDLE_PAGE_STATS, rel, self.d.cfg)
            except OSError:
                continue
            cold = procfs.parse_idle_page_stats(raw).get("cold", 0) * 4096
            total += cold
            self.d.cache.append(
                mc.COLD_PAGE_BYTES, float(cold), {"pod_uid": pod.uid}, ts=now
            )
        self.d.cache.append(mc.COLD_PAGE_BYTES, float(total), ts=now)


class CPICollector:
    """Cycles-per-instruction per pod via the native perf shim
    (collectors/performance — the libpfm perf-group path,
    ``performance_collector_linux.go:101-110``). Gated on CPICollector and
    on the native library + kernel perf actually working here."""

    name = "cpi"
    #: cap on perf fds this collector may hold (each counter costs
    #: 2*n_cpus fds; unbounded growth would exhaust RLIMIT_NOFILE and take
    #: the whole agent's file IO down with it)
    FD_BUDGET = 512

    def __init__(self, deps: _Deps, n_cpus: int = 0):
        self.d = deps
        self.n_cpus = n_cpus or (os.cpu_count() or 1)
        self._counters: dict[str, object] = {}
        self._last: dict[str, tuple[int, int]] = {}

    def _open_counters(self) -> int:
        return sum(1 for c in self._counters.values() if c)

    def enabled(self) -> bool:
        from koordinator_tpu import native
        from koordinator_tpu.features import KOORDLET_GATES

        # Libpfm4 gates the underlying perf machinery (the reference
        # inits libpfm only behind it); CPICollector gates the collector
        return (KOORDLET_GATES.enabled("CPICollector")
                and KOORDLET_GATES.enabled("Libpfm4")
                and native.available())

    def _counter_for(self, key: str, rel: str) -> Optional[object]:
        from koordinator_tpu import native

        counter = self._counters.get(key)
        if counter is None:
            fds_needed = 2 * self.n_cpus
            # at least one counter is always allowed, however many CPUs —
            # otherwise big hosts would silently get no CPI at all
            max_counters = max(1, self.FD_BUDGET // fds_needed)
            if self._open_counters() >= max_counters:
                return None  # over budget: skip WITHOUT caching, so a freed
                             # slot (pod deletion) lets this pod in later
            path = self.d.cfg.cgroup_abs_path("perf_event", rel)
            counter = native.CPICounter(path, self.n_cpus)
            if not counter.open():
                counter = False  # mark unusable, don't retry every tick
            self._counters[key] = counter
        return counter or None

    def _sample(self, key: str, rel: str, metric: str, labels: dict,
                now: float) -> None:
        counter = self._counter_for(key, rel)
        if counter is None:
            return
        sample = counter.read()
        if sample is None:
            return
        cycles, instructions = sample
        last = self._last.get(key)
        self._last[key] = (cycles, instructions)
        if last is None:
            return
        d_cycles, d_instructions = cycles - last[0], instructions - last[1]
        if d_instructions > 0:
            cpi = d_cycles / d_instructions
            self.d.cache.append(metric, cpi, labels, ts=now)
            if metric == mc.CONTAINER_CPI:
                from koordinator_tpu import metrics

                metrics.container_cpi.set(cpi, labels=labels)

    def collect(self) -> None:
        now = self.d.clock()
        live = set()
        for pod in self.d.states.get_all_pods():
            if not pod.is_running:
                continue
            live.add(pod.uid)
            self._sample(pod.uid, pod.cgroup_dir(self.d.cfg), mc.POD_CPI,
                         {"pod_uid": pod.uid}, now)
            for container in pod.containers:
                key = f"{pod.uid}/{container.container_id}"
                live.add(key)
                crel = container.cgroup_dir or self.d.cfg.container_cgroup_dir(
                    pod.kube_qos, pod.uid, container.container_id
                )
                self._sample(
                    key, crel, mc.CONTAINER_CPI,
                    {"pod_uid": pod.uid, "container_id": container.container_id},
                    now,
                )
        for key in [k for k in self._counters if k not in live]:
            counter = self._counters.pop(key)
            if counter:
                counter.close()
            self._last.pop(key, None)


class HostApplicationCollector:
    """Usage of declared host applications (out-of-k8s daemons) by their
    cgroup dirs (collectors/hostapplication)."""

    name = "hostapplication"

    def __init__(self, deps: _Deps, host_apps: dict[str, str] | None = None):
        self.d = deps
        #: app name -> cgroup rel dir
        self.host_apps = host_apps or {}
        self._cpu = _CgroupCPUTracker(deps.cfg)

    def enabled(self) -> bool:
        return bool(self.host_apps)

    def collect(self) -> None:
        now = self.d.clock()
        for app, rel in self.host_apps.items():
            cores = self._cpu.usage_cores(app, rel, now)
            labels = {"app": app}
            if cores is not None:
                self.d.cache.append(mc.HOST_APP_CPU_USAGE, cores, labels, ts=now)
            try:
                mem = float(cg.cgroup_read(cg.MEMORY_USAGE, rel, self.d.cfg))
                self.d.cache.append(mc.HOST_APP_MEMORY_USAGE, mem, labels, ts=now)
            except (OSError, ValueError):
                pass


class NodeInfoCollector:
    """Node CPU model/topology snapshot (collectors/nodeinfo): lscpu-style
    topology + NUMA layout stored in the metric cache's KV side, consumed by
    the NodeResourceTopology reporter and the cpu-normalization plugin."""

    name = "nodeinfo"

    def __init__(self, deps: _Deps):
        self.d = deps

    def enabled(self) -> bool:
        return os.path.exists(
            os.path.join(self.d.cfg.sys_root, "devices", "system", "cpu")
        ) or os.path.exists(self.d.cfg.proc_path("cpuinfo"))

    def collect(self) -> None:
        topo = procfs.read_cpu_topology(self.d.cfg)
        self.d.cache.set_kv(mc.KV_NODE_CPU_INFO, topo)
        numa: dict[int, list[int]] = {}
        for cpu in topo.cpus:
            numa.setdefault(cpu.node, []).append(cpu.cpu)
        self.d.cache.set_kv(mc.KV_NODE_NUMA_INFO, numa)


class NodeStorageInfoCollector:
    """Disk IO rates + utilization (collectors/nodestorageinfo): per
    whole-disk read/write bytes per second and io-ticks utilization derived
    from consecutive /proc/diskstats samples."""

    name = "nodestorageinfo"

    def __init__(self, deps: _Deps):
        self.d = deps
        self._last: dict[str, tuple[float, procfs.DiskStat]] = {}

    def enabled(self) -> bool:
        return os.path.exists(self.d.cfg.proc_path("diskstats"))

    def collect(self) -> None:
        now = self.d.clock()
        stats = procfs.read_diskstats(self.d.cfg)
        for dev, cur in stats.items():
            prev = self._last.get(dev)
            self._last[dev] = (now, cur)
            if prev is None:
                continue
            t0, p = prev
            dt = max(now - t0, 1e-9)
            labels = {"device": dev}
            self.d.cache.append(
                mc.NODE_DISK_READ_RATE,
                max(cur.read_bytes - p.read_bytes, 0) / dt, labels, ts=now,
            )
            self.d.cache.append(
                mc.NODE_DISK_WRITE_RATE,
                max(cur.written_bytes - p.written_bytes, 0) / dt,
                labels, ts=now,
            )
            util = max(cur.io_ticks_ms - p.io_ticks_ms, 0) / (dt * 1000.0)
            self.d.cache.append(
                mc.NODE_DISK_IO_UTIL, min(util * 100.0, 100.0), labels, ts=now
            )


class PageCacheCollector:
    """Node + per-pod page cache (collectors/pagecache): node Cached from
    /proc/meminfo, pod cache from memory.stat total_cache (v1) / file (v2)."""

    name = "pagecache"

    def __init__(self, deps: _Deps):
        self.d = deps

    def enabled(self) -> bool:
        return os.path.exists(self.d.cfg.proc_path("meminfo"))

    def collect(self) -> None:
        now = self.d.clock()
        mem = procfs.read_meminfo(self.d.cfg)
        self.d.cache.append(mc.PAGE_CACHE_BYTES, float(mem.cached), ts=now)
        for pod in self.d.states.get_all_pods():
            rel = pod.cgroup_dir(self.d.cfg)
            try:
                raw = cg.cgroup_read(cg.MEMORY_STAT, rel, self.d.cfg)
            except OSError:
                continue
            cache = 0
            for line in raw.splitlines():
                parts = line.split()
                if len(parts) == 2 and parts[0] in ("total_cache", "file"):
                    cache = int(parts[1])
                    break
            self.d.cache.append(
                mc.PAGE_CACHE_BYTES, float(cache), {"pod_uid": pod.uid}, ts=now
            )


class ResctrlCollector:
    """Per-QoS-group LLC occupancy + memory-bandwidth rate
    (collectors/resctrl): reads resctrl mon_data of the LS/LSR/BE groups the
    resctrl hook/qos plugin maintains."""

    name = "resctrl"

    def __init__(self, deps: _Deps):
        self.d = deps
        self._last_mbm: dict[str, tuple[float, int]] = {}

    def enabled(self) -> bool:
        from koordinator_tpu.features import KOORDLET_GATES
        from koordinator_tpu.koordlet.system.resctrl import ResctrlFS

        return (
            KOORDLET_GATES.enabled("ResctrlCollector")
            and ResctrlFS(self.d.cfg).available()
        )

    def _mon_value(self, group: str, filename: str) -> int:
        from koordinator_tpu.koordlet.system.resctrl import ResctrlFS

        fs = ResctrlFS(self.d.cfg)
        base = os.path.join(fs.group_dir(group), "mon_data")
        total = 0
        found = False
        if not os.path.isdir(base):
            raise OSError(f"no mon_data for {group}")
        for domain in sorted(os.listdir(base)):
            path = os.path.join(base, domain, filename)
            if os.path.isfile(path):
                with open(path) as f:
                    total += int(f.read().strip())
                found = True
        if not found:
            raise OSError(f"no {filename} under {base}")
        return total

    def collect(self) -> None:
        from koordinator_tpu.koordlet.system import resctrl as rc

        now = self.d.clock()
        for group in rc.ALL_GROUPS:
            try:
                occ = self._mon_value(group, "llc_occupancy")
                self.d.cache.append(
                    mc.RESCTRL_LLC_OCCUPANCY, float(occ),
                    {"group": group}, ts=now,
                )
            except OSError:
                pass
            try:
                total = self._mon_value(group, "mbm_total_bytes")
            except OSError:
                continue
            prev = self._last_mbm.get(group)
            self._last_mbm[group] = (now, total)
            if prev is None:
                continue
            t0, v0 = prev
            rate = max(total - v0, 0) / max(now - t0, 1e-9)
            self.d.cache.append(
                mc.RESCTRL_MBM_TOTAL_RATE, rate, {"group": group}, ts=now
            )


class MetricsAdvisor:
    """The collector registry + driver (metricsadvisor/framework)."""

    def __init__(self, states: StatesInformer, cache: mc.MetricCache,
                 cfg: Optional[SystemConfig] = None, clock=time.time,
                 host_apps: dict[str, str] | None = None):
        deps = _Deps(states, cache, cfg, clock)
        self.deps = deps
        from koordinator_tpu.koordlet.devices import (
            AcceleratorCollector,
            HamiVGPUCollector,
            RdmaCollector,
            XpuCollector,
        )

        self.collectors: list[Collector] = [
            NodeResourceCollector(deps),
            PodResourceCollector(deps),
            BEResourceCollector(deps),
            SysResourceCollector(deps),
            PodThrottledCollector(deps),
            PSICollector(deps),
            ColdMemoryCollector(deps),
            CPICollector(deps),
            HostApplicationCollector(deps, host_apps),
            NodeInfoCollector(deps),
            NodeStorageInfoCollector(deps),
            PageCacheCollector(deps),
            ResctrlCollector(deps),
            AcceleratorCollector(deps),
            RdmaCollector(deps),
            XpuCollector(deps),
            HamiVGPUCollector(deps),
        ]

    def collect_once(self) -> list[str]:
        """One tick of every enabled collector; returns the names that ran."""
        ran = []
        for collector in self.collectors:
            try:
                if collector.enabled():
                    collector.collect()
                    ran.append(collector.name)
            except (OSError, ValueError):
                # One garbled kernel file must not kill the whole tick.
                continue
        return ran

    def build_device(self, node_name: str):
        """The koordlet-side Device CR (devices/gpu Infos() -> Device
        reporting): aggregate every enabled device collector's inventory.
        The standalone koord-device-daemon probes independently; this is
        the in-agent path the reference's gpu collector uses."""
        from koordinator_tpu.api import crds

        infos = []
        seen: set[tuple[str, int]] = set()
        for collector in self.collectors:
            if not hasattr(collector, "device_infos"):
                continue
            try:
                if collector.enabled():
                    for info in collector.device_infos():
                        # two collectors can observe the same chip (sysfs
                        # accel class AND a vendor's xpu JSON drop share
                        # the Accelerators gate): first collector wins per
                        # (type, minor), matching device_daemon prober
                        # precedence
                        key = (info.type, info.minor)
                        if key in seen:
                            continue
                        seen.add(key)
                        infos.append(info)
            except (OSError, ValueError):
                continue
        return crds.Device(node_name=node_name, devices=tuple(infos))
