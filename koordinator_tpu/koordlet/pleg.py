"""Pod lifecycle event generator (reference: ``pkg/koordlet/pleg/pleg.go:81``
— inotify watches on the per-QoS cgroup dirs; a pod dir appearing/vanishing
IS the lifecycle signal, independent of the apiserver).

The kernel-portable rebuild scans the three kube-QoS cgroup trees per tick
and diffs against the previous scan (inotify is an optimization the fake-fs
test layer can't exercise; the scan path is the behavior contract).
"""

from __future__ import annotations

import dataclasses
import os
import re
from typing import Callable

from koordinator_tpu.koordlet.system.config import SystemConfig

#: cgroupfs 'pod<uid>' and systemd 'kubepods[-tier]-pod<uid>.slice' layouts
POD_DIR_RE = re.compile(r"(?:kubepods(?:-[a-z]+)?-)?pod([0-9a-zA-Z_-]+?)(?:\.slice)?")


def _normalize_uid(raw: str) -> str:
    """systemd escapes '-' as '_' in pod slice names; undo it."""
    return raw.replace("_", "-")

EVENT_POD_ADDED = "PodAdded"
EVENT_POD_DELETED = "PodDeleted"
EVENT_CONTAINER_ADDED = "ContainerAdded"
EVENT_CONTAINER_DELETED = "ContainerDeleted"


@dataclasses.dataclass(frozen=True)
class PodLifecycleEvent:
    type: str
    pod_uid: str
    container_id: str = ""


class PLEG:
    def __init__(self, cfg: SystemConfig, subsystem: str = "cpu"):
        self.cfg = cfg
        self.subsystem = subsystem
        self._known: dict[str, set[str]] = {}  # pod uid -> container ids
        self._handlers: list[Callable[[PodLifecycleEvent], None]] = []
        #: native inotify gate (libkoordsys ks_watch_*): when armed, quiet
        #: polls skip the tree walk entirely — the reference PLEG is
        #: fsnotify-driven the same way.  The scan-diff below stays the
        #: behavior contract (and the only path on fake filesystems without
        #: churn notification or where inotify is unavailable).
        self._watcher = None
        #: safety net: full rescan at least every N polls even when quiet
        #: (missed events, watch-add races)
        self.rescan_every = 60
        self._quiet_polls = 0
        self.scan_count = 0  # observable in tests

    def add_handler(self, fn: Callable[[PodLifecycleEvent], None]) -> None:
        self._handlers.append(fn)

    # -- native inotify gate -------------------------------------------------

    def start_watch(self) -> bool:
        """Arm the inotify gate over the QoS roots; False (and
        scan-every-poll behavior) unless ALL roots could be watched — a
        partially-armed gate would go dark for pods under a root created
        later (the daemon retries arming each tick until this succeeds).
        Pod-dir watches attach on the first poll's forced scan."""
        from koordinator_tpu.native import DirWatcher

        watcher = DirWatcher()
        if not watcher.open():
            return False
        for qos in ("guaranteed", "burstable", "besteffort"):
            base = self.cfg.cgroup_abs_path(
                self.subsystem, self.cfg.kube_qos_dir(qos))
            if watcher.add(base) is None:
                watcher.close()
                return False
        self._watcher = watcher
        # the first poll after arming must still scan: pods that existed
        # before the watch produce no events but must be reported as added
        # (and that scan attaches their pod-dir watches)
        self._quiet_polls = self.rescan_every
        return True

    def stop_watch(self) -> None:
        if self._watcher is not None:
            self._watcher.close()
            self._watcher = None

    def _sync_pod_watches(self, live: set[str]) -> None:
        """Watch every live pod dir (container churn happens inside them).

        ``live`` is the pod-dir path set the just-finished scan collected
        (one tree walk serves both the diff and the watch set).  Watches
        are (re-)added UNCONDITIONALLY: inotify_add_watch is idempotent,
        and a pod dir deleted+recreated between polls keeps its path but
        lost its kernel watch.  Vanished dirs drop their watches
        kernel-side automatically, so no explicit removal is needed."""
        if self._watcher is None:
            return
        for path in live:
            self._watcher.add(path)

    def _scan(self) -> tuple[dict[str, set[str]], set[str]]:
        """(pod uid -> container ids, pod dir paths) in one walk — the
        paths feed _sync_pod_watches without a second listdir pass."""
        found: dict[str, set[str]] = {}
        pod_paths: set[str] = set()
        for qos in ("guaranteed", "burstable", "besteffort"):
            base = self.cfg.cgroup_abs_path(
                self.subsystem, self.cfg.kube_qos_dir(qos)
            )
            try:
                entries = os.listdir(base)
            except OSError:
                continue
            for entry in entries:
                m = POD_DIR_RE.fullmatch(entry)
                if not m or not os.path.isdir(os.path.join(base, entry)):
                    continue
                uid = _normalize_uid(m.group(1))
                try:
                    containers = {
                        c for c in os.listdir(os.path.join(base, entry))
                        if os.path.isdir(os.path.join(base, entry, c))
                    }
                except OSError:
                    continue  # pod dir vanished between listdir and scan
                found[uid] = containers
                pod_paths.add(os.path.join(base, entry))
        return found, pod_paths

    def poll(self) -> list[PodLifecycleEvent]:
        """Diff the cgroup tree against the last poll; fire + return events.

        With the inotify gate armed, a poll with no pending filesystem
        events (and within the rescan interval) returns immediately
        without walking the tree."""
        if self._watcher is not None:
            changed = bool(self._watcher.poll(0))
            self._quiet_polls += 1
            if not changed and self._quiet_polls < self.rescan_every:
                return []
            self._quiet_polls = 0
        current, pod_paths = self._scan()
        self.scan_count += 1
        if self._watcher is not None:
            self._sync_pod_watches(pod_paths)
        events: list[PodLifecycleEvent] = []
        for uid, containers in current.items():
            if uid not in self._known:
                events.append(PodLifecycleEvent(EVENT_POD_ADDED, uid))
                for cid in sorted(containers):
                    events.append(PodLifecycleEvent(EVENT_CONTAINER_ADDED, uid, cid))
            else:
                prev = self._known[uid]
                for cid in sorted(containers - prev):
                    events.append(PodLifecycleEvent(EVENT_CONTAINER_ADDED, uid, cid))
                for cid in sorted(prev - containers):
                    events.append(PodLifecycleEvent(EVENT_CONTAINER_DELETED, uid, cid))
        for uid in self._known.keys() - current.keys():
            events.append(PodLifecycleEvent(EVENT_POD_DELETED, uid))
        self._known = current
        for event in events:
            for fn in self._handlers:
                fn(event)
        return events
