"""Pod lifecycle event generator (reference: ``pkg/koordlet/pleg/pleg.go:81``
— inotify watches on the per-QoS cgroup dirs; a pod dir appearing/vanishing
IS the lifecycle signal, independent of the apiserver).

The kernel-portable rebuild scans the three kube-QoS cgroup trees per tick
and diffs against the previous scan (inotify is an optimization the fake-fs
test layer can't exercise; the scan path is the behavior contract).
"""

from __future__ import annotations

import dataclasses
import os
import re
from typing import Callable

from koordinator_tpu.koordlet.system.config import SystemConfig

#: cgroupfs 'pod<uid>' and systemd 'kubepods[-tier]-pod<uid>.slice' layouts
POD_DIR_RE = re.compile(r"(?:kubepods(?:-[a-z]+)?-)?pod([0-9a-zA-Z_-]+?)(?:\.slice)?")


def _normalize_uid(raw: str) -> str:
    """systemd escapes '-' as '_' in pod slice names; undo it."""
    return raw.replace("_", "-")

EVENT_POD_ADDED = "PodAdded"
EVENT_POD_DELETED = "PodDeleted"
EVENT_CONTAINER_ADDED = "ContainerAdded"
EVENT_CONTAINER_DELETED = "ContainerDeleted"


@dataclasses.dataclass(frozen=True)
class PodLifecycleEvent:
    type: str
    pod_uid: str
    container_id: str = ""


class PLEG:
    def __init__(self, cfg: SystemConfig, subsystem: str = "cpu"):
        self.cfg = cfg
        self.subsystem = subsystem
        self._known: dict[str, set[str]] = {}  # pod uid -> container ids
        self._handlers: list[Callable[[PodLifecycleEvent], None]] = []

    def add_handler(self, fn: Callable[[PodLifecycleEvent], None]) -> None:
        self._handlers.append(fn)

    def _scan(self) -> dict[str, set[str]]:
        found: dict[str, set[str]] = {}
        for qos in ("guaranteed", "burstable", "besteffort"):
            base = self.cfg.cgroup_abs_path(
                self.subsystem, self.cfg.kube_qos_dir(qos)
            )
            try:
                entries = os.listdir(base)
            except OSError:
                continue
            for entry in entries:
                m = POD_DIR_RE.fullmatch(entry)
                if not m or not os.path.isdir(os.path.join(base, entry)):
                    continue
                uid = _normalize_uid(m.group(1))
                try:
                    containers = {
                        c for c in os.listdir(os.path.join(base, entry))
                        if os.path.isdir(os.path.join(base, entry, c))
                    }
                except OSError:
                    continue  # pod dir vanished between listdir and scan
                found[uid] = containers
        return found

    def poll(self) -> list[PodLifecycleEvent]:
        """Diff the cgroup tree against the last poll; fire + return events."""
        current = self._scan()
        events: list[PodLifecycleEvent] = []
        for uid, containers in current.items():
            if uid not in self._known:
                events.append(PodLifecycleEvent(EVENT_POD_ADDED, uid))
                for cid in sorted(containers):
                    events.append(PodLifecycleEvent(EVENT_CONTAINER_ADDED, uid, cid))
            else:
                prev = self._known[uid]
                for cid in sorted(containers - prev):
                    events.append(PodLifecycleEvent(EVENT_CONTAINER_ADDED, uid, cid))
                for cid in sorted(prev - containers):
                    events.append(PodLifecycleEvent(EVENT_CONTAINER_DELETED, uid, cid))
        for uid in self._known.keys() - current.keys():
            events.append(PodLifecycleEvent(EVENT_POD_DELETED, uid))
        self._known = current
        for event in events:
            for fn in self._handlers:
                fn(event)
        return events
