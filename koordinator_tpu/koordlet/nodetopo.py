"""Node resource topology reporting (reference: koordlet's NodeTopologyReport
feature — builds the NodeResourceTopology CRD (topology.node.k8s.io) the
NUMA-aware scheduler consumes, from lscpu/sysfs + kubelet cpu-manager state).

Produces per-NUMA-zone capacities plus the detailed CPU topology map
(cpu -> core/socket/node) and the kubelet-reserved/system-QoS CPU sets the
scheduler must avoid when allocating exclusive CPUs.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Mapping, Optional

from koordinator_tpu.koordlet.system import procfs
from koordinator_tpu.koordlet.system.config import SystemConfig, get_config


@dataclasses.dataclass(frozen=True)
class NUMAZone:
    name: str                  # "node0"
    cpu_milli: int
    memory_bytes: int
    cpus: tuple[int, ...]
    #: per-size hugepage counts ("2048kB" -> n), populated behind the
    #: HugePageReport gate (the reference reports zone hugepages on the
    #: NRT the same way)
    hugepages: Mapping[str, int] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass(frozen=True)
class NodeTopology:
    """The NRT payload + koordinator's topology annotations."""

    zones: tuple[NUMAZone, ...]
    cpu_topology: tuple[procfs.CPUInfo, ...]
    kubelet_reserved_cpus: tuple[int, ...] = ()
    system_qos_cpus: tuple[int, ...] = ()
    cpu_manager_policy: str = "none"

    def to_annotations(self) -> dict[str, str]:
        """The node-side annotations the scheduler's topology options read."""
        hugepage_zones = {
            z.name: dict(z.hugepages) for z in self.zones if z.hugepages
        }
        out_hugepages = (
            {"node.koordinator.sh/hugepages": json.dumps(
                hugepage_zones, sort_keys=True)}
            if hugepage_zones else {}
        )
        return {
            **out_hugepages,
            "node.koordinator.sh/cpu-topology": json.dumps({
                "detail": [
                    {"cpu": c.cpu, "core": c.core, "socket": c.socket,
                     "node": c.node}
                    for c in self.cpu_topology
                ],
            }, sort_keys=True),
            "node.koordinator.sh/reserved-cpus": procfs.format_cpu_list(
                list(self.kubelet_reserved_cpus)
            ),
            "kubelet.koordinator.sh/cpu-manager-policy": json.dumps(
                {"policy": self.cpu_manager_policy}, sort_keys=True
            ),
        }


class NodeTopologyReporter:
    def __init__(self, cfg: Optional[SystemConfig] = None,
                 memory_per_zone: Optional[Mapping[int, int]] = None,
                 kubelet_reserved_cpus: tuple[int, ...] = (),
                 cpu_manager_policy: str = "none"):
        self.cfg = cfg or get_config()
        self.memory_per_zone = dict(memory_per_zone or {})
        self.kubelet_reserved_cpus = kubelet_reserved_cpus
        self.cpu_manager_policy = cpu_manager_policy

    def _zone_memory(self, node: int) -> int:
        if node in self.memory_per_zone:
            return self.memory_per_zone[node]
        # /sys/devices/system/node/nodeN/meminfo: "Node N MemTotal: X kB"
        path = self.cfg.sys_path("devices", "system", "node", f"node{node}",
                                 "meminfo")
        try:
            with open(path) as f:
                for line in f:
                    if "MemTotal" in line:
                        return int(line.split()[-2]) * 1024
        except (OSError, ValueError, IndexError):
            pass
        return 0

    def _zone_hugepages(self, node: int) -> dict[str, int]:
        """Per-size nr_hugepages for one NUMA zone, behind HugePageReport
        (sysfs: node<N>/hugepages/hugepages-<size>/nr_hugepages)."""
        from koordinator_tpu.features import KOORDLET_GATES

        if not KOORDLET_GATES.enabled("HugePageReport"):
            return {}
        base = self.cfg.sys_path("devices", "system", "node", f"node{node}",
                                 "hugepages")
        out: dict[str, int] = {}
        try:
            sizes = sorted(os.listdir(base))
        except OSError:
            return {}
        for entry in sizes:
            if not entry.startswith("hugepages-"):
                continue
            try:
                with open(os.path.join(base, entry, "nr_hugepages")) as f:
                    out[entry[len("hugepages-"):]] = int(f.read().strip())
            except (OSError, ValueError):
                continue
        return out

    def report(self) -> NodeTopology:
        topology = procfs.read_cpu_topology(self.cfg)
        zones = []
        for node in topology.numa_nodes():
            cpus = tuple(topology.cpus_in_node(node))
            zones.append(NUMAZone(
                name=f"node{node}",
                cpu_milli=len(cpus) * 1000,
                memory_bytes=self._zone_memory(node),
                cpus=cpus,
                hugepages=self._zone_hugepages(node),
            ))
        return NodeTopology(
            zones=tuple(zones),
            cpu_topology=topology.cpus,
            kubelet_reserved_cpus=self.kubelet_reserved_cpus,
            cpu_manager_policy=self.cpu_manager_policy,
        )
