"""The koordlet daemon assembly (reference: ``pkg/koordlet/koordlet.go:60``
``Daemon``, ``:76 NewDaemon``, ``:146 Run``).

Wires the modules into one agent: states informer + metric cache feed the
metrics advisor; the QoS manager and runtime-hook reconciler act through the
shared resource executor; the PLEG nudges reconciliation on pod churn.
``tick`` advances everything one step (tests and the run loop share it).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional

from koordinator_tpu.koordlet import metriccache as mc
from koordinator_tpu.koordlet.audit import Auditor
from koordinator_tpu.koordlet.metricsadvisor import MetricsAdvisor
from koordinator_tpu.koordlet.pleg import PLEG
from koordinator_tpu.koordlet.qosmanager.cpuburst import CPUBurst
from koordinator_tpu.koordlet.qosmanager.cpusuppress import CPUSuppress
from koordinator_tpu.koordlet.qosmanager.evict import (
    AllocatableEvict,
    CPUEvict,
    MemoryEvict,
)
from koordinator_tpu.koordlet.qosmanager.framework import (
    Evictor, QOSManager, StrategyContext,
)
from koordinator_tpu.koordlet.qosmanager.reconcile import (
    BlkIOQOS, CgroupReconcile, ResctrlQOS, SysReconcile,
)
from koordinator_tpu.koordlet.resourceexecutor import ResourceUpdateExecutor
from koordinator_tpu.koordlet.runtimehooks.hooks import HookRegistry
from koordinator_tpu.koordlet.runtimehooks.plugins import register_default_hooks
from koordinator_tpu.koordlet.runtimehooks.reconciler import Reconciler
from koordinator_tpu.koordlet.statesinformer import StatesInformer
from koordinator_tpu.koordlet.system.config import SystemConfig, get_config


class Daemon:
    def __init__(
        self,
        cfg: Optional[SystemConfig] = None,
        audit_dir: Optional[str] = None,
        clock=time.time,
        kill_handler: Optional[Callable] = None,
        device_report_fn: Optional[Callable] = None,
        device_report_interval_seconds: float = 60.0,
        pod_resources_upstream_fn: Optional[Callable] = None,
        informer_sync_interval_seconds: float = 30.0,
    ):
        from koordinator_tpu.features import KOORDLET_GATES

        self.cfg = cfg or get_config()
        self.clock = clock
        # AuditEvents gates recording (the reference's audit events are
        # no-ops unless the gate is on); the CLI's --audit-log-dir still
        # chooses WHERE they go
        self.auditor = (
            Auditor(audit_dir)
            if audit_dir and KOORDLET_GATES.enabled("AuditEvents")
            else None
        )
        self.metric_cache = mc.MetricCache(clock=clock)
        # metric-history persistence (tsdb_storage.go:29 role): restore
        # the previous incarnation's ring buffers so the NodeMetric
        # aggregation windows (p95/p99 over the collect window) survive
        # an agent restart instead of refilling from cold
        self.metric_snapshot_path = os.path.join(
            self.cfg.var_run_root, "metriccache.npz")
        self.metric_cache.restore(self.metric_snapshot_path)
        self.metric_snapshot_interval_seconds = 60.0
        self._last_metric_snapshot = clock()
        self.states = StatesInformer(metric_cache=self.metric_cache, clock=clock)
        self.executor = ResourceUpdateExecutor(self.cfg, self.auditor)
        self.advisor = MetricsAdvisor(
            self.states, self.metric_cache, self.cfg, clock
        )
        ctx = StrategyContext(
            self.states, self.metric_cache, self.executor, self.cfg,
            auditor=self.auditor, clock=clock,
        )
        self.strategy_ctx = ctx
        self.evictor = Evictor(ctx, kill_handler)
        suppress = CPUSuppress(ctx)
        self.qos_manager = QOSManager(ctx, [
            suppress,
            CPUEvict(ctx, self.evictor, suppress.be_real_limit_milli),
            MemoryEvict(ctx, self.evictor),
            AllocatableEvict(ctx, self.evictor, resource="cpu"),
            AllocatableEvict(ctx, self.evictor, resource="memory"),
            CPUBurst(ctx),
            CgroupReconcile(ctx),
            ResctrlQOS(ctx),
            BlkIOQOS(ctx),
            SysReconcile(ctx),
        ])
        self.hook_registry = HookRegistry()
        self.hooks = register_default_hooks(
            self.hook_registry,
            node_slo=ctx.node_slo,
        )
        from koordinator_tpu.koordlet.runtimehooks.plugins import (
            ResctrlUpdater,
        )

        self.hook_reconciler = Reconciler(
            self.states, self.hook_registry, self.executor, self.cfg,
            resctrl_updater=ResctrlUpdater(self.cfg),
        )
        from koordinator_tpu.koordlet.prediction_server import PredictServer

        self.predict_server = PredictServer(
            self.states, self.metric_cache,
            checkpoint_dir=(
                os.path.join(self.cfg.var_run_root, "prediction-checkpoints")
            ),
            clock=clock,
        )
        from koordinator_tpu.koordlet.pod_resources import PodResourcesProxy

        #: pod-resources reverse proxy (PodResourcesProxy gate): served on
        #: the HTTP gateway when the binary attaches one;
        #: ``pod_resources_upstream_fn`` is the kubelet stub seam (returns
        #: the kubelet pod-resources listing dict; None = no upstream, the
        #: proxy reports only koord-allocated devices)
        self.pod_resources = PodResourcesProxy(
            self.states, upstream_list_fn=pod_resources_upstream_fn)
        #: HTTP gateway attached by the binary (--http-port); owned by the
        #: daemon lifecycle so stop() closes its socket and thread
        self.gateway = None
        #: runtime-hook RpcServer attached by the binary
        #: (--runtime-hook-server-addr); same ownership rule
        self.hook_server = None
        self._last_train = 0.0
        self.train_interval_seconds = 60.0
        self.device_report_fn = device_report_fn
        self.device_report_interval_seconds = device_report_interval_seconds
        self._last_device_report = 0.0
        self.pleg = PLEG(self.cfg)
        self.pleg.add_handler(lambda event: self._on_pleg_event(event))
        # arm the native inotify gate (quiet ticks skip the cgroup walk);
        # retried in tick() since the QoS roots may not exist yet at boot
        self._pleg_watch_armed = self.pleg.start_watch()
        self._pleg_dirty = False
        self._last_hook_reconcile = 0.0
        #: periodic safety-net interval even without churn (NodeSLO changes,
        #: missed events); the executor cache keeps quiet passes write-free
        self.hook_reconcile_interval_seconds = 60.0
        self.states.register_callback(
            "node-slo", lambda slo: self._mark_dirty()
        )
        #: informer plugins (states_*.go sources: kubelet pods, shell
        #: callbacks); tick TRIGGERS a sync round on this cadence but the
        #: round runs on its own thread — a hung kubelet fetch must never
        #: stall the 1s QoS enforcement loop (the reference runs informer
        #: loops off the enforcement path too).  A fully-failed round
        #: does not stamp the cadence, so recovery retries on the next
        #: tick (bounded by the single in-flight round + fetch timeout).
        from koordinator_tpu.koordlet.statesinformer import InformerRegistry

        self.informers = InformerRegistry()
        self.informer_sync_interval_seconds = informer_sync_interval_seconds
        self._last_informer_sync = float("-inf")
        self._informer_inflight = threading.Event()
        #: kubelet client behind the pods informer (--kubelet-addr);
        #: None when the shell feeds pods directly
        self.kubelet_stub = None
        #: tick-driven reporters (NodeMetricReporter et al) — each owns
        #: its own cadence; tick just gives them the heartbeat
        self.reporters: list = []
        self._reporters_inflight = threading.Event()
        #: RpcClient to a solver sidecar (--scheduler-sidecar-addr)
        self.sidecar_client = None
        self._stop = threading.Event()

    def _on_pleg_event(self, event) -> None:
        self._mark_dirty()

    def _mark_dirty(self) -> None:
        self._pleg_dirty = True

    def tick(self) -> dict:
        """One agent step: sync informers -> collect -> enforce ->
        reconcile on churn/SLO change/interval."""
        now0 = self.clock()
        if (len(self.informers)
                and not self._informer_inflight.is_set()
                and now0 - self._last_informer_sync
                >= self.informer_sync_interval_seconds):
            self._informer_inflight.set()

            def sync_round(stamp=now0):
                try:
                    self.informers.sync_all(self.states)
                    # only a fully-clean round rests for the interval: a
                    # failing plugin (kubelet briefly down) keeps
                    # retrying every tick, bounded by the single
                    # in-flight round + the fetch timeout
                    if not self.informers.sync_errors:
                        self._last_informer_sync = stamp
                finally:
                    self._informer_inflight.clear()

            threading.Thread(target=sync_round, daemon=True).start()
        collected = self.advisor.collect_once()
        # reporters AFTER collection (a due report ships this tick's
        # samples) and OFF the enforcement thread (a wedged sidecar
        # socket blocks its push up to the RPC timeout); failures are
        # counted by each reporter (report_failures), never raised
        if self.reporters and not self._reporters_inflight.is_set():
            self._reporters_inflight.set()

            def reporter_round():
                try:
                    for reporter in self.reporters:
                        try:
                            reporter.tick()
                        except Exception:  # noqa: BLE001
                            pass
                finally:
                    self._reporters_inflight.clear()

            threading.Thread(target=reporter_round, daemon=True).start()
        strategies = self.qos_manager.tick()
        if not self._pleg_watch_armed:
            self._pleg_watch_armed = self.pleg.start_watch()
        self.pleg.poll()
        writes = 0
        now = self.clock()
        due = (
            now - self._last_hook_reconcile >= self.hook_reconcile_interval_seconds
        )
        if self._pleg_dirty or due:
            writes = self.hook_reconciler.reconcile_once()
            self._pleg_dirty = False
            self._last_hook_reconcile = now
        if (now - self._last_metric_snapshot
                >= self.metric_snapshot_interval_seconds):
            try:
                self.metric_cache.snapshot(self.metric_snapshot_path)
            except OSError:  # full/readonly disk must not stall the loop
                pass
            self._last_metric_snapshot = now
        if now - self._last_train >= self.train_interval_seconds:
            self.predict_server.gc()
            self.predict_server.train_once()
            self._last_train = now
        if (self.device_report_fn is not None
                and now - self._last_device_report
                >= self.device_report_interval_seconds):
            # Device CR reporting (devices/gpu Infos() path): the shell
            # pushes this to the apiserver / sync service.  Until the
            # informer knows the node, hold off WITHOUT stamping the
            # timer — the first valid report must not wait a full extra
            # interval behind an anonymous one.
            node = self.states.get_node()
            if node is not None:
                self.device_report_fn(
                    self.advisor.build_device(node.name))
                self._last_device_report = now
        return {
            "collected": collected,
            "strategies": strategies,
            "hook_writes": writes,
        }

    def run(self, interval_seconds: float = 1.0) -> None:  # pragma: no cover
        while not self._stop.is_set():
            self.tick()
            self._stop.wait(interval_seconds)

    def stop(self) -> None:
        self._stop.set()
        # final snapshot on shutdown (SIGTERM path: the binaries call
        # stop()) so the next incarnation restores up-to-the-second
        # windows, matching the TSDB's on-node persistence
        try:
            self.metric_cache.snapshot(self.metric_snapshot_path)
        except OSError:
            pass
        self.pleg.stop_watch()
        if self.gateway is not None:
            self.gateway.stop()
            self.gateway = None
        if self.hook_server is not None:
            self.hook_server.stop()
            self.hook_server = None
        if self.sidecar_client is not None:
            self.sidecar_client.close()
            self.sidecar_client = None
