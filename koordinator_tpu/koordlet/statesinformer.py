"""States informer: the agent's view of node/pods/NodeSLO + callback fan-out
(reference: ``pkg/koordlet/statesinformer/api.go:117-131`` interface,
``impl/states_*.go`` per-state plugins, NodeMetric reporter
``impl/states_nodemetric.go:206``).

The reference watches the kube-apiserver and the kubelet; here sources are
pluggable feeders (the control-plane bridge, a kubelet stub, or tests calling
``set_pods``/``set_node`` directly) and consumers register typed callbacks.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Mapping, Optional

from koordinator_tpu.api.qos import QoSClass
from koordinator_tpu.koordlet import metriccache as mc
from koordinator_tpu.koordlet.system.config import SystemConfig, get_config

# Callback registration types (statesinformer.RegisterType).
TYPE_NODE = "node"
TYPE_ALL_PODS = "all-pods"
TYPE_NODE_SLO = "node-slo"
TYPE_NODE_METRIC = "node-metric"
TYPE_DEVICE = "device"


@dataclasses.dataclass(frozen=True)
class ContainerMeta:
    name: str
    container_id: str
    cgroup_dir: str = ""


@dataclasses.dataclass(frozen=True)
class PodMeta:
    """Node-side pod model: what the agent needs from a v1.Pod."""

    uid: str
    name: str
    namespace: str
    qos_class: QoSClass
    kube_qos: str                        # guaranteed|burstable|besteffort
    priority: int = 0
    phase: str = "Running"
    requests: Mapping[str, int] = dataclasses.field(default_factory=dict)
    limits: Mapping[str, int] = dataclasses.field(default_factory=dict)
    containers: tuple[ContainerMeta, ...] = ()
    annotations: Mapping[str, str] = dataclasses.field(default_factory=dict)
    labels: Mapping[str, str] = dataclasses.field(default_factory=dict)
    host_network: bool = False
    #: task ids from the pod cgroup's cgroup.procs (resctrl task binding)
    pids: tuple[int, ...] = ()

    def cgroup_dir(self, cfg: SystemConfig | None = None) -> str:
        cfg = cfg or get_config()
        return cfg.pod_cgroup_dir(self.kube_qos, self.uid)

    @property
    def is_running(self) -> bool:
        return self.phase == "Running"


@dataclasses.dataclass(frozen=True)
class NodeInfo:
    name: str
    allocatable: Mapping[str, int] = dataclasses.field(default_factory=dict)
    capacity: Mapping[str, int] = dataclasses.field(default_factory=dict)
    labels: Mapping[str, str] = dataclasses.field(default_factory=dict)
    annotations: Mapping[str, str] = dataclasses.field(default_factory=dict)


class StatesInformer:
    """Holds current state, fans out change callbacks, reports NodeMetric."""

    def __init__(self, metric_cache: Optional[mc.MetricCache] = None,
                 clock=time.time):
        self._lock = threading.Lock()
        self._node: Optional[NodeInfo] = None
        self._pods: dict[str, PodMeta] = {}
        self._pods_synced = False
        self._node_slo: Optional[object] = None
        self._device: Optional[object] = None
        self._callbacks: dict[str, list[Callable]] = {}
        self.metric_cache = metric_cache
        self._clock = clock

    # -- registration ---------------------------------------------------------

    def register_callback(self, state_type: str, fn: Callable) -> None:
        with self._lock:
            self._callbacks.setdefault(state_type, []).append(fn)

    def _fire(self, state_type: str, payload) -> None:
        with self._lock:
            fns = list(self._callbacks.get(state_type, []))
        for fn in fns:
            fn(payload)

    # -- writers (fed by sources) --------------------------------------------

    def set_node(self, node: NodeInfo) -> None:
        with self._lock:
            self._node = node
        self._fire(TYPE_NODE, node)

    def set_pods(self, pods: list[PodMeta]) -> None:
        with self._lock:
            self._pods = {p.uid: p for p in pods}
            self._pods_synced = True
        self._fire(TYPE_ALL_PODS, pods)

    def set_node_slo(self, node_slo) -> None:
        with self._lock:
            self._node_slo = node_slo
        self._fire(TYPE_NODE_SLO, node_slo)

    def set_device(self, device) -> None:
        with self._lock:
            self._device = device
        self._fire(TYPE_DEVICE, device)

    # -- readers --------------------------------------------------------------

    def get_node(self) -> Optional[NodeInfo]:
        with self._lock:
            return self._node

    def get_all_pods(self) -> list[PodMeta]:
        with self._lock:
            return list(self._pods.values())

    @property
    def pods_synced(self) -> bool:
        """True once the pod informer has delivered at least one (possibly
        empty) pod list — destructive GC sweeps must wait for this, or the
        first tick after an agent restart treats every running pod as dead."""
        with self._lock:
            return self._pods_synced

    def get_pod(self, uid: str) -> Optional[PodMeta]:
        with self._lock:
            return self._pods.get(uid)

    def get_node_slo(self):
        with self._lock:
            return self._node_slo

    # -- NodeMetric reporting -------------------------------------------------

    def build_node_metric(self, window_seconds: float = 300.0,
                          report_percentiles: bool = True,
                          now: float | None = None):
        """Aggregate the metric cache into a NodeMetric status
        (states_nodemetric.go sync loop). Returns api.crds.NodeMetricStatus.
        ``now`` lets a caller with its own clock (the reporter) keep the
        window and the freshness check on one timeline."""
        from koordinator_tpu.api.crds import (
            AggregatedUsage, NodeMetricStatus, PodMetricInfo, ResourceUsage,
        )

        assert self.metric_cache is not None, "metric cache required"
        if now is None:
            now = self._clock()
        start = now - window_seconds

        def usage_of(metric_cpu, metric_mem, labels=None) -> ResourceUsage:
            cpu = self.metric_cache.query(metric_cpu, labels, start, now)
            mem = self.metric_cache.query(metric_mem, labels, start, now)
            return ResourceUsage(cpu_milli=int(cpu.avg() * 1000),
                                 memory_bytes=int(mem.avg()))

        node_usage = usage_of(mc.NODE_CPU_USAGE, mc.NODE_MEMORY_USAGE)
        sys_usage = usage_of(mc.SYS_CPU_USAGE, mc.SYS_MEMORY_USAGE)

        aggregated = None
        if report_percentiles:
            cpu_q = self.metric_cache.query(mc.NODE_CPU_USAGE, None, start, now)
            mem_q = self.metric_cache.query(mc.NODE_MEMORY_USAGE, None, start, now)
            aggregated = AggregatedUsage(
                cpu_milli_p={
                    q: int(cpu_q.percentile(q) * 1000)
                    for q in (0.5, 0.9, 0.95, 0.99)
                },
                memory_bytes_p={
                    q: int(mem_q.percentile(q)) for q in (0.5, 0.9, 0.95, 0.99)
                },
                duration_seconds=cpu_q.duration_seconds(),
            )

        pods_metrics = []
        for pod in self.get_all_pods():
            labels = {"pod_uid": pod.uid}
            pods_metrics.append(
                PodMetricInfo(
                    namespace=pod.namespace, name=pod.name, uid=pod.uid,
                    usage=usage_of(mc.POD_CPU_USAGE, mc.POD_MEMORY_USAGE, labels),
                    priority=pod.priority,
                    qos_class=pod.qos_class.name,
                )
            )

        return NodeMetricStatus(
            update_time=now,
            node_usage=node_usage,
            system_usage=sys_usage,
            aggregated_node_usage=aggregated,
            pods_metrics=tuple(pods_metrics),
        )


# ---- pluggable informer registry (impl/states_informer.go) -----------------

class InformerPlugin:
    """One state source (impl/states_*.go shape): ``sync`` pulls its state
    into the shared StatesInformer; ``depends`` names plugins whose first
    sync must land earlier (the reference starts informers in dependency
    order — e.g. the pods informer needs the node first for filtering)."""

    name = "informer"
    depends: tuple[str, ...] = ()

    def sync(self, states: "StatesInformer") -> None:  # pragma: no cover
        raise NotImplementedError


class InformerRegistry:
    """Owns plugins, topologically orders them, drives sync rounds."""

    def __init__(self) -> None:
        self._plugins: dict[str, InformerPlugin] = {}
        self.sync_errors: dict[str, str] = {}

    def register(self, plugin: InformerPlugin) -> None:
        if plugin.name in self._plugins:
            raise ValueError(f"informer {plugin.name!r} already registered")
        self._plugins[plugin.name] = plugin

    def __len__(self) -> int:
        return len(self._plugins)

    def ordered(self) -> list[InformerPlugin]:
        """Dependency order (states_informer.go starts in listed order with
        HasSynced gates; this is the same constraint as a topo sort)."""
        seen: dict[str, int] = {}   # 0 = visiting, 1 = done
        out: list[InformerPlugin] = []

        def visit(name: str) -> None:
            mark = seen.get(name)
            if mark == 1:
                return
            if mark == 0:
                raise ValueError(f"informer dependency cycle at {name!r}")
            seen[name] = 0
            plugin = self._plugins.get(name)
            if plugin is None:
                raise ValueError(f"unknown informer dependency {name!r}")
            for dep in plugin.depends:
                visit(dep)
            seen[name] = 1
            out.append(plugin)

        for name in sorted(self._plugins):
            visit(name)
        return out

    def sync_all(self, states: "StatesInformer") -> int:
        """One sync round over every plugin in dependency order; a failing
        plugin records its error and does not block the others (informer
        callbacks are isolated in the reference too). Returns successes."""
        try:
            plugins = self.ordered()
        except ValueError:
            # a broken dependency declaration must not silence every other
            # informer: drop plugins whose dep chains don't resolve, record
            # their error, order the rest
            plugins, resolved = [], set()
            progressed = True
            names = set(self._plugins)
            while progressed:
                progressed = False
                for name in sorted(names - resolved):
                    plugin = self._plugins[name]
                    if all(d in resolved for d in plugin.depends
                           if d in names) and all(
                               d in names for d in plugin.depends):
                        plugins.append(plugin)
                        resolved.add(name)
                        progressed = True
            for name in sorted(names - resolved):
                self.sync_errors[name] = "unresolved informer dependencies"
        ok = 0
        for plugin in plugins:
            try:
                plugin.sync(states)
                self.sync_errors.pop(plugin.name, None)
                ok += 1
            except Exception as e:
                self.sync_errors[plugin.name] = repr(e)
        return ok


class CallbackInformer(InformerPlugin):
    """Adapter: any shell-provided fetch callable as an informer plugin
    (the states_node/states_device informers are apiserver watches in the
    reference; the deployment shell owns that transport here)."""

    def __init__(self, name: str, sync_fn, depends: tuple[str, ...] = ()):
        self.name = name
        self.depends = depends
        self._sync_fn = sync_fn

    def sync(self, states: "StatesInformer") -> None:
        self._sync_fn(states)


class KubeletPodsInformer(InformerPlugin):
    """impl/states_pods.go: pods come from the kubelet, not the apiserver."""

    name = "pods"
    depends = ("node",)

    def __init__(self, stub) -> None:
        self.stub = stub

    def sync(self, states: "StatesInformer") -> None:
        states.set_pods(self.stub.get_all_pods())


class NodeMetricReporter:
    """impl/states_nodemetric.go:206 — the sync worker: every
    ``report_interval`` (pushed by the manager through the NodeMetric
    spec), aggregate the window and report; when the metric cache has gone
    silent past the expiration budget, report a DEGRADED status instead of
    stale numbers (nodeMetric expired handling)."""

    def __init__(self, states: StatesInformer,
                 report_fn: Callable[[object], None],
                 report_interval_seconds: float = 60.0,
                 aggregate_window_seconds: float = 300.0,
                 expire_seconds: float = 180.0,
                 clock=time.time):
        if states.metric_cache is None:
            raise ValueError("NodeMetricReporter requires a StatesInformer "
                             "with a metric cache")
        self.states = states
        self.report_fn = report_fn
        self.report_interval_seconds = report_interval_seconds
        self.aggregate_window_seconds = aggregate_window_seconds
        self.expire_seconds = expire_seconds
        self.clock = clock
        self._last_report = float("-inf")   # first tick reports immediately
        self.reports = 0
        self.degraded_reports = 0
        #: report_fn raised (e.g. the sidecar push failed): the report
        #: interval still rests (retry next interval, not next tick) but
        #: the failure is COUNTED — a swallowed push error must be
        #: visible somewhere
        self.report_failures = 0

    def update_spec(self, report_interval_seconds: float,
                    aggregate_window_seconds: float) -> None:
        """Manager pushed a new NodeMetric spec (collect policy)."""
        self.report_interval_seconds = report_interval_seconds
        self.aggregate_window_seconds = aggregate_window_seconds

    def _fresh(self, now: float) -> bool:
        cache = self.states.metric_cache
        res = cache.query(mc.NODE_CPU_USAGE, None,
                          now - self.expire_seconds, now)
        return not res.empty

    def tick(self) -> Optional[object]:
        """Report when due; returns the reported status (or None)."""
        now = self.clock()
        if now - self._last_report < self.report_interval_seconds:
            return None
        self._last_report = now
        if not self._fresh(now):
            from koordinator_tpu.api.crds import NodeMetricStatus

            status = NodeMetricStatus(update_time=now, degraded=True)
            degraded = True
        else:
            status = self.states.build_node_metric(
                window_seconds=self.aggregate_window_seconds, now=now)
            degraded = False
        try:
            self.report_fn(status)
        except Exception:  # noqa: BLE001 — the transport's failure, not
            # the reporter's; the interval rests (no hammering a down
            # sidecar) and the next interval retries
            self.report_failures += 1
            return None
        if degraded:
            self.degraded_reports += 1
        else:
            self.reports += 1
        self.states._fire(TYPE_NODE_METRIC, status)
        return status
