"""States informer: the agent's view of node/pods/NodeSLO + callback fan-out
(reference: ``pkg/koordlet/statesinformer/api.go:117-131`` interface,
``impl/states_*.go`` per-state plugins, NodeMetric reporter
``impl/states_nodemetric.go:206``).

The reference watches the kube-apiserver and the kubelet; here sources are
pluggable feeders (the control-plane bridge, a kubelet stub, or tests calling
``set_pods``/``set_node`` directly) and consumers register typed callbacks.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Mapping, Optional

from koordinator_tpu.api.qos import QoSClass
from koordinator_tpu.koordlet import metriccache as mc
from koordinator_tpu.koordlet.system.config import SystemConfig, get_config

# Callback registration types (statesinformer.RegisterType).
TYPE_NODE = "node"
TYPE_ALL_PODS = "all-pods"
TYPE_NODE_SLO = "node-slo"
TYPE_NODE_METRIC = "node-metric"
TYPE_DEVICE = "device"


@dataclasses.dataclass(frozen=True)
class ContainerMeta:
    name: str
    container_id: str
    cgroup_dir: str = ""


@dataclasses.dataclass(frozen=True)
class PodMeta:
    """Node-side pod model: what the agent needs from a v1.Pod."""

    uid: str
    name: str
    namespace: str
    qos_class: QoSClass
    kube_qos: str                        # guaranteed|burstable|besteffort
    priority: int = 0
    phase: str = "Running"
    requests: Mapping[str, int] = dataclasses.field(default_factory=dict)
    limits: Mapping[str, int] = dataclasses.field(default_factory=dict)
    containers: tuple[ContainerMeta, ...] = ()
    annotations: Mapping[str, str] = dataclasses.field(default_factory=dict)
    labels: Mapping[str, str] = dataclasses.field(default_factory=dict)
    host_network: bool = False

    def cgroup_dir(self, cfg: SystemConfig | None = None) -> str:
        cfg = cfg or get_config()
        return cfg.pod_cgroup_dir(self.kube_qos, self.uid)

    @property
    def is_running(self) -> bool:
        return self.phase == "Running"


@dataclasses.dataclass(frozen=True)
class NodeInfo:
    name: str
    allocatable: Mapping[str, int] = dataclasses.field(default_factory=dict)
    capacity: Mapping[str, int] = dataclasses.field(default_factory=dict)
    labels: Mapping[str, str] = dataclasses.field(default_factory=dict)
    annotations: Mapping[str, str] = dataclasses.field(default_factory=dict)


class StatesInformer:
    """Holds current state, fans out change callbacks, reports NodeMetric."""

    def __init__(self, metric_cache: Optional[mc.MetricCache] = None,
                 clock=time.time):
        self._lock = threading.Lock()
        self._node: Optional[NodeInfo] = None
        self._pods: dict[str, PodMeta] = {}
        self._node_slo: Optional[object] = None
        self._device: Optional[object] = None
        self._callbacks: dict[str, list[Callable]] = {}
        self.metric_cache = metric_cache
        self._clock = clock

    # -- registration ---------------------------------------------------------

    def register_callback(self, state_type: str, fn: Callable) -> None:
        with self._lock:
            self._callbacks.setdefault(state_type, []).append(fn)

    def _fire(self, state_type: str, payload) -> None:
        with self._lock:
            fns = list(self._callbacks.get(state_type, []))
        for fn in fns:
            fn(payload)

    # -- writers (fed by sources) --------------------------------------------

    def set_node(self, node: NodeInfo) -> None:
        with self._lock:
            self._node = node
        self._fire(TYPE_NODE, node)

    def set_pods(self, pods: list[PodMeta]) -> None:
        with self._lock:
            self._pods = {p.uid: p for p in pods}
        self._fire(TYPE_ALL_PODS, pods)

    def set_node_slo(self, node_slo) -> None:
        with self._lock:
            self._node_slo = node_slo
        self._fire(TYPE_NODE_SLO, node_slo)

    def set_device(self, device) -> None:
        with self._lock:
            self._device = device
        self._fire(TYPE_DEVICE, device)

    # -- readers --------------------------------------------------------------

    def get_node(self) -> Optional[NodeInfo]:
        with self._lock:
            return self._node

    def get_all_pods(self) -> list[PodMeta]:
        with self._lock:
            return list(self._pods.values())

    def get_pod(self, uid: str) -> Optional[PodMeta]:
        with self._lock:
            return self._pods.get(uid)

    def get_node_slo(self):
        with self._lock:
            return self._node_slo

    # -- NodeMetric reporting -------------------------------------------------

    def build_node_metric(self, window_seconds: float = 300.0,
                          report_percentiles: bool = True):
        """Aggregate the metric cache into a NodeMetric status
        (states_nodemetric.go sync loop). Returns api.crds.NodeMetricStatus.
        """
        from koordinator_tpu.api.crds import (
            AggregatedUsage, NodeMetricStatus, PodMetricInfo, ResourceUsage,
        )

        assert self.metric_cache is not None, "metric cache required"
        now = self._clock()
        start = now - window_seconds

        def usage_of(metric_cpu, metric_mem, labels=None) -> ResourceUsage:
            cpu = self.metric_cache.query(metric_cpu, labels, start, now)
            mem = self.metric_cache.query(metric_mem, labels, start, now)
            return ResourceUsage(cpu_milli=int(cpu.avg() * 1000),
                                 memory_bytes=int(mem.avg()))

        node_usage = usage_of(mc.NODE_CPU_USAGE, mc.NODE_MEMORY_USAGE)
        sys_usage = usage_of(mc.SYS_CPU_USAGE, mc.SYS_MEMORY_USAGE)

        aggregated = None
        if report_percentiles:
            cpu_q = self.metric_cache.query(mc.NODE_CPU_USAGE, None, start, now)
            mem_q = self.metric_cache.query(mc.NODE_MEMORY_USAGE, None, start, now)
            aggregated = AggregatedUsage(
                cpu_milli_p={
                    q: int(cpu_q.percentile(q) * 1000)
                    for q in (0.5, 0.9, 0.95, 0.99)
                },
                memory_bytes_p={
                    q: int(mem_q.percentile(q)) for q in (0.5, 0.9, 0.95, 0.99)
                },
                duration_seconds=cpu_q.duration_seconds(),
            )

        pods_metrics = []
        for pod in self.get_all_pods():
            labels = {"pod_uid": pod.uid}
            pods_metrics.append(
                PodMetricInfo(
                    namespace=pod.namespace, name=pod.name, uid=pod.uid,
                    usage=usage_of(mc.POD_CPU_USAGE, mc.POD_MEMORY_USAGE, labels),
                    priority=pod.priority,
                    qos_class=pod.qos_class.name,
                )
            )

        return NodeMetricStatus(
            update_time=now,
            node_usage=node_usage,
            system_usage=sys_usage,
            aggregated_node_usage=aggregated,
            pods_metrics=tuple(pods_metrics),
        )
