"""Batched preemption: victim-subset selection as a tensor solve.

The reference implements preemption in three cooperating places:

- elastic-quota victim selection (`pkg/scheduler/plugins/elasticquota/preempt.go:111`
  ``SelectVictimsOnNode``): remove every lower-priority same-quota pod from the
  node, check the preemptor fits, then *reprieve* victims most-important-first
  (PDB-violating candidates get the first chance to come back), keeping a pod
  evicted only when adding it back would break the node fit or push the quota
  past its used limit; ``canPreempt`` (`preempt.go:289`) restricts candidates to
  lower-priority, preemptible pods of the same quota.
- gang/job-level preemption (`pkg/scheduler/plugins/coscheduling/core/preemption.go:206`
  ``Preempt``): a whole gang's pending pods preempt together, all-or-nothing;
  victims are lower-priority pods (`:405 isPreemptionAllowed`), reprieve order
  is priority-descending (`:819 sortVictims`).
- reservation PostFilter (`pkg/scheduler/plugins/reservation/plugin.go:1058`):
  a reservation's reserve-pod preempts like an ordinary pod.

The TPU redesign collapses the per-node dry-run loops into ONE scan over the
globally-sorted candidate list: each scan step touches only its candidate's
node row, so per-node reprieve order is preserved while every node's dry run
advances in the same pass.  Node selection afterwards is the upstream
``pickOneNodeForPreemption`` lexicographic rule as a sequence of masked
reductions.

PDB semantics (`preempt.go:224 filterPodsWithPDBViolation`): a candidate is
"violating" when evicting it would exceed its PDB's remaining disruption
budget, counted per (node, pdb) in importance order — violating candidates are
reprieved first so the chosen victim set violates as few budgets as possible,
and the winning node minimizes violations lexicographically first.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS
from koordinator_tpu.quota.admission import HEADROOM_CLAMP
from koordinator_tpu.state.cluster_state import ClusterState

#: sentinel priority placed below any real koordinator priority band
NEG_PRI = jnp.int32(-(2**31) + 1)

#: fully-open quota headroom for preemptors without a quota inside
#: ``preempt_chain`` (same clamp bound the admission path uses, so the
#: +freed arithmetic in select_victims cannot overflow int32)
HEADROOM_OPEN = HEADROOM_CLAMP


@struct.dataclass
class ScheduledPods:
    """Bound (running) pods — the victim-candidate universe. Shape (V, ...)."""

    requests: jax.Array        # (V, R) int32
    node: jax.Array            # (V,) int32 — node row the pod is bound to
    priority: jax.Array        # (V,) int32
    quota_id: jax.Array        # (V,) int32, -1 = none
    non_preemptible: jax.Array # (V,) bool — extension.IsPodNonPreemptible
    pdb_id: jax.Array          # (V,) int32, -1 = no PDB matches
    valid: jax.Array           # (V,) bool

    @property
    def capacity(self) -> int:
        return self.requests.shape[0]

    @classmethod
    def build(
        cls,
        requests: np.ndarray,          # (v, R)
        node: np.ndarray,              # (v,)
        priority: np.ndarray | None = None,
        quota_id: np.ndarray | None = None,
        non_preemptible: np.ndarray | None = None,
        pdb_id: np.ndarray | None = None,
        capacity: int | None = None,
    ) -> "ScheduledPods":
        v = len(requests)
        cap = capacity if capacity is not None else max(8, 1 << max(v - 1, 0).bit_length())
        req = np.zeros((cap, requests.shape[1] if v else NUM_RESOURCE_DIMS), np.int32)
        req[:v] = requests

        def pad1(a, fill, dtype):
            out = np.full(cap, fill, dtype=dtype)
            if a is not None:
                out[:v] = a
            return jnp.asarray(out)

        valid = np.zeros(cap, bool)
        valid[:v] = True
        return cls(
            requests=jnp.asarray(req),
            node=pad1(node, -1, np.int32),
            priority=pad1(priority, 0, np.int32),
            quota_id=pad1(quota_id, -1, np.int32),
            non_preemptible=pad1(non_preemptible, False, bool),
            pdb_id=pad1(pdb_id, -1, np.int32),
            valid=jnp.asarray(valid),
        )


def _fits(req: jnp.ndarray, free: jnp.ndarray) -> jnp.ndarray:
    """(..., R) fit check with the fit_mask convention (req==0 never blocks)."""
    return jnp.all((req <= free) | (req == 0), axis=-1)


def _pdb_violating(
    cand: jnp.ndarray,        # (V,) bool
    order: jnp.ndarray,       # (V,) candidate indices, importance-descending
    node: jnp.ndarray,        # (V,) int32
    pdb_id: jnp.ndarray,      # (V,) int32
    pdb_allowed: jnp.ndarray, # (B,) int32 disruptionsAllowed
    node_capacity: int,
) -> jnp.ndarray:
    """(V,) bool: per-(node, pdb) rank in importance order >= remaining budget.

    Mirrors filterPodsWithPDBViolation: walking a node's candidates
    most-important-first, each PDB match decrements that budget; a candidate
    whose decrement takes the budget negative is "violating".
    """
    b = pdb_allowed.shape[0]
    # segment id per candidate: node * B + pdb (only meaningful when pdb >= 0)
    has_pdb = cand & (pdb_id >= 0)
    seg = jnp.where(has_pdb, node * b + jnp.maximum(pdb_id, 0), node_capacity * b)
    seg_in_order = seg[order]
    # rank within segment, respecting the importance order: stable-sort the
    # ordered list by segment, cumsum inside runs of equal segment.
    pos = jnp.argsort(seg_in_order, stable=True)
    seg_sorted = seg_in_order[pos]
    ones = jnp.ones_like(seg_sorted)
    csum = jnp.cumsum(ones) - 1                       # 0..V-1 over sorted list
    is_start = jnp.concatenate(
        [jnp.array([True]), seg_sorted[1:] != seg_sorted[:-1]]
    )
    start_of_seg = jnp.where(is_start, csum, 0)
    start = jax.lax.associative_scan(jnp.maximum, start_of_seg)
    rank_sorted = csum - start                        # 0-based rank in segment
    # scatter ranks back: first to order positions, then to candidate rows
    rank_in_order = jnp.zeros_like(rank_sorted).at[pos].set(rank_sorted)
    rank = jnp.zeros(node.shape[0], rank_in_order.dtype).at[order].set(rank_in_order)
    allowed = pdb_allowed[jnp.maximum(pdb_id, 0)]
    return has_pdb & (rank >= allowed)


@struct.dataclass
class VictimSolve:
    """Per-node dry-run result for one preemptor."""

    eligible: jax.Array       # (N,) bool — preemptor fits after preemption
    victim: jax.Array         # (V,) bool — victims (across all nodes)
    violating: jax.Array      # (V,) bool — PDB-violating candidates
    num_victims: jax.Array    # (N,) int32
    num_violating: jax.Array  # (N,) int32
    max_victim_pri: jax.Array # (N,) int32 (NEG_PRI when none)
    sum_victim_pri: jax.Array # (N,) int32 (band priorities — see solve)


def select_victims(
    state: ClusterState,
    sched: ScheduledPods,
    preemptor_req: jnp.ndarray,      # (R,) int32
    preemptor_pri: jnp.ndarray,      # () int32
    preemptor_quota: jnp.ndarray,    # () int32, -1 = none
    pod_feasible: jnp.ndarray,       # (N,) bool — affinity/selector mask
    pdb_allowed: jnp.ndarray,        # (B,) int32
    quota_headroom: jnp.ndarray | None = None,  # (R,) int32: limit - used
    same_quota_only: bool = False,
) -> VictimSolve:
    """Dry-run victim selection on every node at once.

    ``same_quota_only=True`` gives elastic-quota semantics (canPreempt,
    preempt.go:289): only lower-priority pods of the preemptor's quota are
    candidates, and ``quota_headroom`` gates the reprieve the way
    postFilterState.usedLimit does.  ``False`` gives the job-preemption rule
    (isPreemptionAllowed, coscheduling preemption.go:405): any lower-priority
    preemptible pod.  May be a traced scalar bool (``preempt_chain`` mixes
    both kinds in one scan); a traced value requires ``quota_headroom`` to
    be an array (pass fully-open headroom for the non-quota case).
    """
    n_cap = state.capacity

    cand = (
        sched.valid
        & (sched.priority < preemptor_pri)
        & ~sched.non_preemptible
        & (sched.node >= 0)
    )
    if isinstance(same_quota_only, bool):
        if same_quota_only:
            cand = cand & (sched.quota_id == preemptor_quota)
    else:
        cand = cand & (
            ~same_quota_only | (sched.quota_id == preemptor_quota)
        )

    # importance-descending candidate order (sortVictims: priority desc, then
    # a stable tiebreak — we use row index)
    pri_key = jnp.where(cand, sched.priority, NEG_PRI)
    imp_order = jnp.lexsort((jnp.arange(sched.capacity), -pri_key))

    violating = _pdb_violating(
        cand, imp_order, sched.node, sched.pdb_id, pdb_allowed, n_cap
    )

    # reprieve order: violating first, then non-violating, importance-desc in
    # each group; non-candidates last
    group = jnp.where(cand, jnp.where(violating, 0, 1), 2)
    order = jnp.lexsort((jnp.arange(sched.capacity), -pri_key, group))

    # start state: every candidate removed from its node
    safe_node = jnp.maximum(sched.node, 0)
    freed = jax.ops.segment_sum(
        jnp.where(cand[:, None], sched.requests, 0), safe_node,
        num_segments=n_cap,
    )
    free_all = state.free + freed
    has_cand = (
        jax.ops.segment_sum(cand.astype(jnp.int32), safe_node, num_segments=n_cap)
        > 0
    )

    if quota_headroom is not None:
        # per-node quota dry run: each node's cycle state starts with its own
        # candidates' requests returned to the quota
        quota_free0 = quota_headroom[None, :] + freed
    else:
        quota_free0 = None

    def step(carry, j):
        free_all, quota_free = carry
        nd = safe_node[j]
        is_cand = cand[j]
        req = sched.requests[j]
        after_node = free_all[nd] - req
        ok = _fits(preemptor_req, after_node)
        if quota_free is not None:
            ok = ok & _fits(preemptor_req, quota_free[nd] - req)
        reprieve = is_cand & ok
        dec = jnp.where(reprieve, req, 0)
        free_all = free_all.at[nd].add(-dec)
        if quota_free is not None:
            quota_free = quota_free.at[nd].add(-dec)
        return (free_all, quota_free), is_cand & ~ok

    (free_final, quota_final), victim_in_order = jax.lax.scan(
        step, (free_all, quota_free0), order
    )
    victim = jnp.zeros(sched.capacity, bool).at[order].set(victim_in_order)

    eligible = (
        _fits(preemptor_req, free_final)
        & pod_feasible
        & state.node_valid
        & has_cand
    )
    if quota_final is not None:
        eligible = eligible & _fits(preemptor_req, quota_final)

    v_pri = jnp.where(victim, sched.priority, NEG_PRI)
    num_victims = jax.ops.segment_sum(
        victim.astype(jnp.int32), safe_node, num_segments=n_cap
    )
    num_violating = jax.ops.segment_sum(
        (victim & violating).astype(jnp.int32), safe_node, num_segments=n_cap
    )
    max_victim_pri = jax.ops.segment_max(v_pri, safe_node, num_segments=n_cap)
    max_victim_pri = jnp.where(num_victims > 0, max_victim_pri, NEG_PRI)
    # Deliberately int32: priorities here are koordinator bands (<= ~10k,
    # api/priority.py), so the per-node sum is exact up to ~200k victims on
    # one node — far beyond any real node's pod count.  (int64 would need
    # jax x64 mode, which the rest of the solver doesn't enable.)
    sum_victim_pri = jax.ops.segment_sum(
        jnp.where(victim, sched.priority, 0),
        safe_node, num_segments=n_cap,
    )
    return VictimSolve(
        eligible=eligible,
        victim=victim,
        violating=violating,
        num_victims=num_victims,
        num_violating=num_violating,
        max_victim_pri=max_victim_pri,
        sum_victim_pri=sum_victim_pri,
    )


def pick_node(solve: VictimSolve) -> jnp.ndarray:
    """Upstream pickOneNodeForPreemption lexicographic rule:

    1. fewest PDB violations, 2. lowest highest-victim priority, 3. lowest
    priority sum, 4. fewest victims, 5. (no start-times here) lowest node row.
    Returns () int32 node row, -1 when no node is eligible.
    """
    mask = solve.eligible

    def refine(mask, key):
        # sentinel must dominate any real key value in the key's own dtype
        big = jnp.iinfo(key.dtype).max
        key_m = jnp.where(mask, key, big)
        return mask & (key == jnp.min(key_m))

    mask = refine(mask, solve.num_violating)
    mask = refine(mask, solve.max_victim_pri)
    mask = refine(mask, solve.sum_victim_pri)
    mask = refine(mask, solve.num_victims)
    idx = jnp.argmax(mask)  # lowest eligible row
    return jnp.where(jnp.any(solve.eligible), idx.astype(jnp.int32), -1)


@struct.dataclass
class PreemptionOutcome:
    node: jax.Array          # () int32, -1 = preemption does not help
    victims: jax.Array       # (V,) bool — victims on the chosen node only
    state: ClusterState      # node_requested with victims removed + preemptor nominated
    sched: ScheduledPods     # victims invalidated
    pdb_allowed: jax.Array   # (B,) decremented for evicted PDB members


def preempt_one(
    state: ClusterState,
    sched: ScheduledPods,
    preemptor_req: jnp.ndarray,
    preemptor_pri: jnp.ndarray,
    preemptor_quota: jnp.ndarray,
    pod_feasible: jnp.ndarray,
    pdb_allowed: jnp.ndarray,
    quota_headroom: jnp.ndarray | None = None,
    same_quota_only: bool = False,
    nominate: bool = True,
) -> PreemptionOutcome:
    """Full PostFilter for one preemptor: dry-run, pick node, commit.

    Commit removes the victims' requests from node accounting, invalidates
    them in ``sched``, charges their PDBs, and (``nominate=True``) reserves the
    preemptor's request on the chosen node so subsequent preemptors see it —
    the nominated-pod semantics of the upstream preemption cycle.
    """
    solve = select_victims(
        state, sched, preemptor_req, preemptor_pri, preemptor_quota,
        pod_feasible, pdb_allowed, quota_headroom=quota_headroom,
        same_quota_only=same_quota_only,
    )
    node = pick_node(solve)
    chosen = solve.victim & (sched.node == node) & (node >= 0)

    # remove victims from node accounting in one scatter
    delta = jnp.where(chosen[:, None], sched.requests, 0)
    removed = jax.ops.segment_sum(
        delta, jnp.maximum(sched.node, 0), num_segments=state.capacity
    )
    requested = state.node_requested - removed
    if nominate:
        nom = jnp.where(node >= 0, preemptor_req, 0)
        requested = requested.at[jnp.maximum(node, 0)].add(nom)
    new_state = state.replace(node_requested=requested)

    new_sched = sched.replace(valid=sched.valid & ~chosen)

    pdb_hit = jax.ops.segment_sum(
        (chosen & (sched.pdb_id >= 0)).astype(jnp.int32),
        jnp.maximum(sched.pdb_id, 0),
        num_segments=pdb_allowed.shape[0],
    )
    new_pdb = pdb_allowed - pdb_hit
    return PreemptionOutcome(
        node=node, victims=chosen, state=new_state, sched=new_sched,
        pdb_allowed=new_pdb,
    )


@struct.dataclass
class ChainOutcome:
    """Per-preemptor results of :func:`preempt_chain` (leading axis C)."""

    node: jax.Array          # (C,) int32, -1 = failed / inactive
    victims: jax.Array       # (C, V) bool — victims per successful preemptor
    state: ClusterState      # final state after all successful preemptors
    sched: ScheduledPods     # final sched
    pdb_allowed: jax.Array   # (B,) final budgets


def preempt_chain(
    state: ClusterState,
    sched: ScheduledPods,
    reqs: jnp.ndarray,          # (C, R) int32
    pris: jnp.ndarray,          # (C,) int32
    qids: jnp.ndarray,          # (C,) int32, -1 = none
    feasible: jnp.ndarray,      # (C, N) bool
    same_quota: jnp.ndarray,    # (C,) bool — elastic-quota vs job rule
    active: jnp.ndarray,        # (C,) bool — padding rows are inactive
    pdb_allowed: jnp.ndarray,   # (B,) int32
    base_headroom: jnp.ndarray, # (Q, R) int32 runtime - used per quota row
) -> ChainOutcome:
    """Chain C single-pod PostFilter dry-runs inside ONE device program.

    Semantically identical to calling :func:`preempt_one` sequentially per
    preemptor with the host committing each success in between (the
    scheduler's per-pod loop), but with one jit dispatch per chunk instead
    of per failed pod — the bounded-work answer to a quota-starved 50k
    queue (upstream bounds preemption work per cycle the same way,
    coscheduling preemption.go:206).

    Cross-preemptor quota effects are carried in-scan: a success charges
    the preemptor's quota row with its request and releases every victim's
    request to the victim's own quota row, mirroring the tree commit
    (`q.used` update + nomination assume) the host performs between
    sequential calls.  Failed or inactive rows leave the carry untouched.
    """
    q_rows = base_headroom.shape[0] if base_headroom is not None else 1
    base_hr = (
        jnp.full((max(q_rows, 1), reqs.shape[1]), HEADROOM_OPEN, jnp.int32)
        if base_headroom is None else base_headroom.astype(jnp.int32)
    )

    def step(carry, x):
        requested, valid, pdb, assumed = carry
        req, pri, qid, feas, sq, act = x
        cur_state = state.replace(node_requested=requested)
        cur_sched = sched.replace(valid=valid)
        safe_q = jnp.maximum(qid, 0)
        hr = jnp.where(
            sq, base_hr[safe_q] - assumed[safe_q], HEADROOM_OPEN
        )
        hr = jnp.clip(hr, -HEADROOM_OPEN, HEADROOM_OPEN)
        out = preempt_one(
            cur_state, cur_sched, req, pri, qid, feas, pdb,
            quota_headroom=hr, same_quota_only=sq,
        )
        ok = act & (out.node >= 0)
        chosen = out.victims & ok

        # quota commit mirror: victims release to their own quota rows,
        # the preemptor charges its row (nomination assume)
        vic_by_q = jax.ops.segment_sum(
            jnp.where(chosen[:, None] & (sched.quota_id >= 0)[:, None],
                      sched.requests, 0),
            jnp.maximum(sched.quota_id, 0), num_segments=base_hr.shape[0],
        )
        add = jnp.where(ok & (qid >= 0), req, 0)
        assumed = (assumed - vic_by_q).at[safe_q].add(add)

        new_carry = (
            jnp.where(ok, out.state.node_requested, requested),
            jnp.where(ok, out.sched.valid, valid),
            jnp.where(ok, out.pdb_allowed, pdb),
            assumed,
        )
        return new_carry, (jnp.where(ok, out.node, -1), chosen)

    assumed0 = jnp.zeros_like(base_hr)
    carry0 = (state.node_requested, sched.valid, pdb_allowed, assumed0)
    (requested, valid, pdb, _), (nodes, victims) = jax.lax.scan(
        step, carry0, (reqs, pris, qids, feasible, same_quota, active)
    )
    return ChainOutcome(
        node=nodes, victims=victims,
        state=state.replace(node_requested=requested),
        sched=sched.replace(valid=valid),
        pdb_allowed=pdb,
    )
