"""Reservation-aware scheduling kernels.

TPU-native equivalent of the reference's reservation plugin
(pkg/scheduler/plugins/reservation/: plugin.go, transformer.go restore path,
scoring.go, nominator.go). The reference models a Reservation as a "reserve
pod" that occupies node resources (apis/scheduling/v1alpha1/
reservation_types.go:250); pods matching the reservation's owners may then
allocate out of the reserved-but-unallocated remainder. Here the whole
reservation set is a fixed-capacity tensor struct and the restore/fit/score
logic is batched over (pods x reservations) / (pods x nodes).

Accounting invariant: when a reservation becomes Available on a node, the host
charges its full reserved vector to that node's ``node_requested`` (the
reserve-pod trick, snapshot.reserve). So plain pods already cannot see the
reserved capacity; these kernels hand the *remaining* (reserved - allocated)
back to owner-matched pods only.

Allocate policies (reservation_types.go:81-99):
- Aligned (default): an owner pod allocates from the reservation first and any
  spill comes from ordinary node free capacity.
- Restricted: for every resource named in the reservation, the pod's request
  must fit entirely within the reservation's remainder; unreserved dims spill
  to node free.
AllocateOnce (reservation_types.go:60-64): first successful owner consumes the
whole reservation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS
from koordinator_tpu.ops import scoring
from koordinator_tpu.state.cluster_state import ClusterState, PodBatch, _bucket


@struct.dataclass
class ReservationSet:
    """Fixed-capacity padded reservation tensors (V rows)."""

    valid: jax.Array          # (V,) bool — row holds an Available reservation
    node_idx: jax.Array       # (V,) int32 — node the reservation sits on, -1 none
    reserved: jax.Array       # (V, R) int32 — total reserved (reservation allocatable)
    allocated: jax.Array      # (V, R) int32 — currently allocated to owner pods
    allocate_once: jax.Array  # (V,) bool
    restricted: jax.Array     # (V,) bool — Restricted vs Aligned policy

    @property
    def capacity(self) -> int:
        return self.valid.shape[0]

    @property
    def active(self) -> jax.Array:
        """(V,) bool — row holds a valid reservation PLACED on a node;
        the one definition of usability shared by remaining /
        reservation_fit / allocate_from_reservation."""
        return self.valid & (self.node_idx >= 0)

    @property
    def remaining(self) -> jax.Array:
        """(V, R) reserved-but-unallocated, zero for invalid/unplaced rows."""
        return jnp.where(self.active[:, None],
                         self.reserved - self.allocated, 0)

    @classmethod
    def zeros(cls, capacity: int = 16, dims: int = NUM_RESOURCE_DIMS) -> "ReservationSet":
        return cls(
            valid=jnp.zeros(capacity, bool),
            node_idx=jnp.full(capacity, -1, jnp.int32),
            reserved=jnp.zeros((capacity, dims), jnp.int32),
            allocated=jnp.zeros((capacity, dims), jnp.int32),
            allocate_once=jnp.zeros(capacity, bool),
            restricted=jnp.zeros(capacity, bool),
        )

    @classmethod
    def build(
        cls,
        reserved: np.ndarray,           # (V, R)
        node_idx: np.ndarray,           # (V,)
        allocated: np.ndarray | None = None,
        allocate_once: np.ndarray | None = None,
        restricted: np.ndarray | None = None,
        capacity: int | None = None,
    ) -> "ReservationSet":
        n = len(reserved)
        cap = capacity or _bucket(max(n, 1), minimum=16)
        dims = reserved.shape[1] if n else NUM_RESOURCE_DIMS

        def pad2(a):
            out = np.zeros((cap, dims), np.int32)
            out[:n] = a
            return jnp.asarray(out)

        def pad1(a, fill, dtype):
            out = np.full(cap, fill, dtype)
            if a is not None:
                out[:n] = a
            return jnp.asarray(out)

        valid = np.zeros(cap, bool)
        valid[:n] = True
        return cls(
            valid=jnp.asarray(valid),
            node_idx=pad1(np.asarray(node_idx, np.int32), -1, np.int32),
            reserved=pad2(reserved),
            allocated=pad2(allocated if allocated is not None else np.zeros_like(reserved)),
            allocate_once=pad1(allocate_once, False, bool),
            restricted=pad1(restricted, False, bool),
        )


def reservation_fit(
    rsv: ReservationSet,
    node_free: jnp.ndarray,    # (N, R) free WITHOUT reservation remainders
    requests: jnp.ndarray,     # (P, R)
    match: jnp.ndarray,        # (P, V) owner-matcher result (host-computed)
) -> jnp.ndarray:
    """(P, V) bool — pod p could allocate through reservation v on its node.

    Mirrors plugin.go's per-reservation fit during Filter with the restore
    transformer applied (transformer.go), per allocate policy.
    """
    rows = jnp.clip(rsv.node_idx, 0)
    free_at = node_free[rows]                       # (V, R)
    rem = rsv.remaining                             # (V, R)
    # Exhausted rows (e.g. consumed allocate-once) are no longer a reservation
    # anyone can allocate through — without this they'd keep the score boost.
    active = rsv.active & jnp.any(rem > 0, axis=-1)
    req = requests[:, None, :]                      # (P, 1, R)

    # req == 0 dims must not exclude (allocatable can shrink below what is
    # already scheduled, leaving free negative in an unrequested dim — same
    # escape as filtering.fit_mask).
    unrequested = req == 0
    aligned_ok = jnp.all((req <= (rem + free_at)[None]) | unrequested, axis=-1)
    dim_reserved = rsv.reserved > 0                 # (V, R)
    restricted_ok = jnp.all(
        jnp.where(dim_reserved[None], req <= rem[None], req <= free_at[None])
        | unrequested,
        axis=-1,
    )
    fits = jnp.where(rsv.restricted[None, :], restricted_ok, aligned_ok)
    return fits & match & active[None, :]


def reservation_node_mask(
    fits: jnp.ndarray,         # (P, V)
    rsv: ReservationSet,
    n_nodes: int,
) -> jnp.ndarray:
    """(P, N) bool — node has at least one fitting matched reservation."""
    onehot = (
        jax.nn.one_hot(jnp.clip(rsv.node_idx, 0), n_nodes, dtype=jnp.int32)
        * (rsv.node_idx >= 0)[:, None]
    )                                               # (V, N)
    return (fits.astype(jnp.int32) @ onehot) > 0


def nominate_reservation(
    fits: jnp.ndarray,         # (P, V)
    rsv: ReservationSet,
    node: jnp.ndarray,         # (P,) chosen node per pod
) -> jnp.ndarray:
    """(P,) int32 — the reservation each pod allocates through, -1 for none.

    Among fitting matched reservations on the chosen node, prefer the one with
    the smallest total remainder (best-fit, keeps big reservations intact —
    the nominator.go preference order reduced to a tensor argmin).
    """
    on_node = fits & (rsv.node_idx[None, :] == node[:, None]) & (node[:, None] >= 0)
    total_rem = jnp.sum(rsv.remaining, axis=-1)     # (V,)
    keyed = jnp.where(on_node, total_rem[None, :], jnp.iinfo(jnp.int32).max)
    best = jnp.argmin(keyed, axis=-1)
    has = jnp.any(on_node, axis=-1)
    return jnp.where(has, best, -1).astype(jnp.int32)


def allocate_from_reservation(
    rsv: ReservationSet,
    r_idx: jnp.ndarray,        # () int32, -1 = no reservation
    request: jnp.ndarray,      # (R,)
) -> tuple[ReservationSet, jnp.ndarray]:
    """Charge one pod's allocation to a reservation row.

    Returns (new_rsv, spill): spill is the part of the request NOT covered by
    the reservation remainder (to be charged to the node). AllocateOnce rows
    are consumed entirely (allocated := reserved).
    """
    use = r_idx >= 0
    row = jnp.clip(r_idx, 0)
    rem = rsv.remaining[row]
    take = jnp.where(use, jnp.minimum(request, rem), 0)
    spill = jnp.where(use, request - take, request)
    # consume-whole only applies to an ACTIVE row: an invalid or
    # unplaced reservation has nothing to give (take is already 0 via
    # remaining), and marking it fully allocated would mutate state a
    # caller never drew from (found by the randomized ledger sweep —
    # unreachable through nominate_reservation, which only returns
    # on-node rows, but a direct caller must not trip it)
    consume_all = use & rsv.active[row] & rsv.allocate_once[row]
    new_alloc_row = jnp.where(
        consume_all, rsv.reserved[row], rsv.allocated[row] + take
    )
    new_allocated = rsv.allocated.at[row].set(
        jnp.where(use, new_alloc_row, rsv.allocated[row])
    )
    return rsv.replace(allocated=new_allocated), spill


def score_pods_with_reservations(
    state: ClusterState,
    pods: PodBatch,
    cfg,
    rsv: ReservationSet,
    match: jnp.ndarray,        # (P, V)
    boost: int = 10_000,
):
    """Batched Filter+Score with reservation restore.

    Returns (scores, feasible, fits): feasibility is extended to nodes
    reachable only through a matched reservation, and such nodes get a score
    boost (ReservationScorePlugin semantics: prefer consuming reservations).
    """
    from koordinator_tpu.ops.assignment import _threshold_mask, score_pods

    scores, feasible = score_pods(state, pods, cfg)
    fits = reservation_fit(rsv, state.free, pods.requests, match)
    via_rsv = reservation_node_mask(fits, rsv, state.capacity)
    # The restore path extends *fit*, not the LoadAware usage-threshold filter:
    # an overloaded node stays infeasible even for owner pods (load_aware.go
    # Filter runs regardless of reservation restore).
    pod_est = scoring.estimate_pod_usage_by_band(
        pods.requests, cfg.estimator_factors, cfg.estimator_defaults
    )
    via_rsv = (
        via_rsv
        & _threshold_mask(cfg, state.node_usage, state.node_agg_usage,
                          state.node_allocatable, pod_est)
        & pods.feasible_rows(state)
        & state.node_valid[None, :]
        & pods.valid[:, None]
    )
    feasible = feasible | via_rsv
    scores = scores + jnp.where(via_rsv, boost, 0)
    return scores, feasible, fits


def reservation_greedy_assign(
    state: ClusterState,
    pods: PodBatch,
    cfg,
    rsv: ReservationSet,
    match: jnp.ndarray,        # (P, V)
    quota=None,
    boost: int = 10_000,
):
    """Sequential assignment with reservation-first accounting.

    Like assignment.greedy_assign but each step: (1) extends feasibility with
    matched reservations, (2) prefers reserved nodes, (3) charges the chosen
    reservation's remainder first and only the spill to node_requested
    (Reserve semantics of plugin.go Reserve + nominator).

    Returns (assignments, rsv_choice, new_state, new_rsv, new_quota).
    """
    from koordinator_tpu.ops.assignment import _greedy_scan

    return _greedy_scan(
        state, pods, cfg, quota=quota, rsv=rsv, match=match, rsv_boost=boost
    )
