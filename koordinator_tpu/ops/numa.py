"""Fine-grained CPU orchestration: cpuset accumulation + NUMA topology hints.

TPU-native equivalent of the reference's nodenumaresource plugin
(pkg/scheduler/plugins/nodenumaresource/: cpu_accumulator.go takeCPUs,
topology hint generation in topology_hint.go) and the scheduler-side
topology manager (pkg/scheduler/frameworkext/topologymanager/: policies
none/best-effort/restricted/single-numa-node).

Design split (mirrors how the reference actually uses this logic):

- **Filter is batched, count-based.** Whether a pod's cpuset request fits a
  node needs only per-NUMA/per-socket free counts — segment-sums over the
  (nodes x cpus) topology tensors, vmapped over every node at once.
- **Reserve is single-node, sort-based.** The actual cpuset selection
  (take-by-topology) runs once on the chosen node: build a lexicographic
  priority key per logical CPU from (eligibility, NUMA-satisfies-alone, NUMA
  allocate strategy, socket/core grouping, sibling rank), argsort, take the
  first n. This replaces the accumulator's nested free-cores-in-node/socket
  walks (cpu_accumulator.go:108-200) with one vectorized sort.

Bind policies (apis/extension/numa_aware.go:101-107): FullPCPUs allocates
whole physical cores; SpreadByPCPUs allocates one sibling per core first.
NUMA allocate strategies: MostAllocated packs the fullest NUMA node first,
LeastAllocated spreads to the emptiest.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from koordinator_tpu.ops.select import take_by_rank
from koordinator_tpu.state.cluster_state import _bucket

#: Hint enumeration bound: masks are enumerated statically as 2^MAX_NUMA
#: combinations (the reference's bitmask.IterateBitMasks over NUMA nodes).
MAX_NUMA = 8

# CPUBindPolicy (numa_aware.go:101-107)
BIND_DEFAULT = 0
BIND_FULL_PCPUS = 1
BIND_SPREAD_BY_PCPUS = 2

# NUMAAllocateStrategy
STRATEGY_MOST_ALLOCATED = 0   # pack: prefer NUMA nodes with least free
STRATEGY_LEAST_ALLOCATED = 1  # spread: prefer NUMA nodes with most free

# CPUExclusivePolicy (numa_aware.go:114-118)
EXCLUSIVE_NONE = 0
EXCLUSIVE_PCPU_LEVEL = 1      # no other pod may share my physical cores
EXCLUSIVE_NUMA_LEVEL = 2      # no other pod may share my NUMA nodes

# Topology manager policies (frameworkext/topologymanager/policy_*.go)
POLICY_NONE = 0
POLICY_BEST_EFFORT = 1
POLICY_RESTRICTED = 2
POLICY_SINGLE_NUMA_NODE = 3


@struct.dataclass
class CPUTopology:
    """Per-logical-CPU topology arrays, padded to a static CPU capacity C."""

    core_of: jax.Array     # (C,) int32 — physical core id (< C)
    numa_of: jax.Array     # (C,) int32 — NUMA node id (< MAX_NUMA)
    socket_of: jax.Array   # (C,) int32
    valid: jax.Array       # (C,) bool

    @property
    def capacity(self) -> int:
        return self.valid.shape[0]

    @classmethod
    def build(
        cls,
        core_of: np.ndarray,
        numa_of: np.ndarray,
        socket_of: np.ndarray,
        capacity: int | None = None,
    ) -> "CPUTopology":
        n = len(core_of)
        cap = capacity or _bucket(max(n, 1), minimum=8)

        def pad(a):
            out = np.zeros(cap, np.int32)
            out[:n] = a
            return jnp.asarray(out)

        valid = np.zeros(cap, bool)
        valid[:n] = True
        return cls(pad(core_of), pad(numa_of), pad(socket_of), jnp.asarray(valid))

    @classmethod
    def uniform(
        cls,
        sockets: int = 1,
        numa_per_socket: int = 1,
        cores_per_numa: int = 4,
        threads_per_core: int = 2,
        capacity: int | None = None,
    ) -> "CPUTopology":
        """Synthetic SMT topology (lscpu-shaped, util/system/lscpu.go)."""
        n = sockets * numa_per_socket * cores_per_numa * threads_per_core
        cpu = np.arange(n)
        core = cpu // threads_per_core
        numa = core // cores_per_numa
        sock = numa // numa_per_socket
        return cls.build(core, numa, sock, capacity=capacity)


def _round_up_to_cores(topo: CPUTopology, n_cpus: jnp.ndarray) -> jnp.ndarray:
    """Round a cpu count up to a multiple of threads-per-core."""
    c = topo.capacity
    core_size = jax.ops.segment_sum(topo.valid.astype(jnp.int32), topo.core_of, c)
    tpc = jnp.maximum(jnp.max(core_size), 1)
    return ((n_cpus + tpc - 1) // tpc) * tpc


def _counts(topo: CPUTopology, free: jnp.ndarray):
    """Shared count tensors: per-core/NUMA free + full-core stats."""
    c = topo.capacity
    core_size = jax.ops.segment_sum(topo.valid.astype(jnp.int32), topo.core_of, c)
    core_free = jax.ops.segment_sum(free.astype(jnp.int32), topo.core_of, c)
    cpu_on_full_core = (core_free[topo.core_of] == core_size[topo.core_of]) & free
    numa_free = jax.ops.segment_sum(free.astype(jnp.int32), topo.numa_of, MAX_NUMA)
    numa_full = jax.ops.segment_sum(
        cpu_on_full_core.astype(jnp.int32), topo.numa_of, MAX_NUMA
    )
    return cpu_on_full_core, numa_free, numa_full


@functools.partial(jax.jit, static_argnames=("full_pcpus",))
def cpuset_fit(
    topo: CPUTopology,
    ref_count: jnp.ndarray,   # (C,) int32 current allocations per cpu
    max_ref: jnp.ndarray,     # () int32 — maxRefCount (1 = exclusive cpus)
    n_cpus: jnp.ndarray,      # () int32 requested logical cpus
    full_pcpus: bool = False,
    banned: jnp.ndarray | None = None,  # (C,) bool exclusivity exclusions
) -> jnp.ndarray:
    """() bool — can this node satisfy the cpuset request at all (Filter)."""
    free = topo.valid & (ref_count < max_ref)
    if banned is not None:
        free = free & ~banned
    cpu_full, _, _ = _counts(topo, free)
    if full_pcpus:
        # Whole-core policy: a non-multiple request rounds up to whole cores
        # (a partially-taken core would reintroduce SMT interference).
        n_eff = _round_up_to_cores(topo, n_cpus)
        return jnp.sum(cpu_full.astype(jnp.int32)) >= n_eff
    return jnp.sum(free.astype(jnp.int32)) >= n_cpus


def cpuset_fit_batched(
    topos: CPUTopology,        # batched (N, C) topology
    ref_counts: jnp.ndarray,   # (N, C)
    max_ref: jnp.ndarray,      # (N,)
    n_cpus: jnp.ndarray,       # ()
    full_pcpus: bool = False,
) -> jnp.ndarray:
    """(N,) bool — vmapped Filter over every node (the batched hot path)."""
    fn = lambda t, rc, mr: cpuset_fit(t, rc, mr, n_cpus, full_pcpus=full_pcpus)
    return jax.vmap(fn)(topos, ref_counts, max_ref)


@functools.partial(jax.jit, static_argnames=("bind_policy", "strategy"))
def take_cpus(
    topo: CPUTopology,
    ref_count: jnp.ndarray,   # (C,)
    max_ref: jnp.ndarray,     # ()
    n_cpus: jnp.ndarray,      # ()
    bind_policy: int = BIND_DEFAULT,
    strategy: int = STRATEGY_MOST_ALLOCATED,
    banned: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Select a cpuset on one node: returns ((C,) bool selection, ok).

    Sort-key construction (one argsort replaces the accumulator's walks):
      1. eligible first (free; FullPCPUs additionally requires a fully-free core)
      2. CPUs whose NUMA node can satisfy the whole request alone
         (the accumulator's fits-in-one-node fast path)
      3. NUMA nodes ordered by allocate strategy (pack vs spread)
      4. same core adjacent (FullPCPUs takes whole cores) or sibling-rank
         round-robin (SpreadByPCPUs takes one sibling per core first)
      5. cpu index (determinism)
    """
    c = topo.capacity
    free = topo.valid & (ref_count < max_ref)
    if banned is not None:
        free = free & ~banned
    cpu_full, numa_free, numa_full = _counts(topo, free)

    full = bind_policy == BIND_FULL_PCPUS
    eligible = cpu_full if full else free
    pool = numa_full if full else numa_free
    if full:
        n_cpus = _round_up_to_cores(topo, n_cpus)  # whole cores only

    # (2) does this cpu's NUMA node alone satisfy the request?
    numa_satisfies = (pool >= n_cpus)[topo.numa_of] & eligible

    # (3) strategy order among NUMA nodes
    numa_key = pool[topo.numa_of]
    if strategy == STRATEGY_MOST_ALLOCATED:
        numa_order = numa_key          # fewest free first
    else:
        numa_order = -numa_key         # most free first

    # (4) sibling rank: position of this cpu among the free cpus of its core
    # (O(C^2) one-node matrix — C is small and this runs once per Reserve).
    same_core = topo.core_of[:, None] == topo.core_of[None, :]
    lower = jnp.arange(c)[None, :] < jnp.arange(c)[:, None]
    sibling_rank = jnp.sum(same_core & lower & free[None, :], axis=-1)
    if bind_policy == BIND_SPREAD_BY_PCPUS:
        intra = sibling_rank * c + topo.core_of    # round-robin over cores
    else:
        intra = topo.core_of * c + sibling_rank    # whole cores together

    return take_by_rank(
        (
            jnp.arange(c),                     # (5)
            intra,                             # (4)
            numa_order,                        # (3)
            ~numa_satisfies,                   # (2)
            ~eligible,                         # (1) — primary
        ),
        eligible,
        n_cpus,
    )


# -- NUMA topology hints + topology manager (frameworkext/topologymanager) ----


def _mask_table() -> jnp.ndarray:
    """(2^MAX_NUMA, MAX_NUMA) bool — every NUMA-node bitmask combination."""
    m = np.arange(1 << MAX_NUMA)
    return jnp.asarray((m[:, None] >> np.arange(MAX_NUMA)) & 1, bool)


_MASKS = _mask_table()
_POPCOUNT = jnp.sum(_MASKS.astype(jnp.int32), axis=-1)


def numa_hints(
    numa_free: jnp.ndarray,    # (MAX_NUMA,) free units per NUMA node
    request: jnp.ndarray,      # () requested units
) -> jnp.ndarray:
    """(2^MAX_NUMA,) bool feasibility per NUMA mask (hint generation).

    A mask is feasible if the free capacity across its member nodes covers
    the request (GenerateMachineInfoHints-style per-provider hints).
    """
    totals = _MASKS.astype(jnp.int32) @ numa_free.astype(jnp.int32)
    nonempty = _POPCOUNT > 0
    return (totals >= request) & nonempty


def preferred_mask(feasible: jnp.ndarray) -> jnp.ndarray:
    """() int32 — the feasible mask with fewest NUMA nodes (-1 if none).

    The topology manager's 'preferred' bit: minimal-width masks win
    (policy.go mergeProvidersHints narrowest-mask preference).
    """
    key = jnp.where(feasible, _POPCOUNT * (1 << MAX_NUMA) + jnp.arange(1 << MAX_NUMA),
                    jnp.iinfo(jnp.int32).max)
    best = jnp.argmin(key)
    return jnp.where(jnp.any(feasible), best, -1).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("policy",))
def merge_hints(
    provider_feasible: jnp.ndarray,  # (K, 2^MAX_NUMA) bool — one row per provider
    policy: int = POLICY_BEST_EFFORT,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Topology manager Admit: merge provider hints under a policy.

    Returns (admit, mask): mask is the chosen NUMA bitmask index (-1 when the
    merge found none; best-effort still admits in that case, matching
    policy_best_effort.go; restricted and single-numa-node reject).
    """
    merged = jnp.all(provider_feasible, axis=0)
    if policy == POLICY_SINGLE_NUMA_NODE:
        merged = merged & (_POPCOUNT == 1)
    best = preferred_mask(merged)
    has = best >= 0
    if policy == POLICY_NONE:
        admit = jnp.bool_(True)
    elif policy == POLICY_BEST_EFFORT:
        admit = jnp.bool_(True)
    else:  # RESTRICTED / SINGLE_NUMA_NODE
        admit = has
    return admit, best


def numa_score(
    numa_free: jnp.ndarray,    # (MAX_NUMA,)
    numa_total: jnp.ndarray,   # (MAX_NUMA,)
    request: jnp.ndarray,      # ()
    strategy: int = STRATEGY_MOST_ALLOCATED,
) -> jnp.ndarray:
    """() int32 in [0, 100] — NUMA-affinity score for one node.

    Fitting inside a single NUMA node is worth half the range; the other half
    follows the allocate strategy applied to the best candidate node
    (score per resource_manager.go's most/least-allocated NUMA scoring).
    """
    fits_single = jnp.any(numa_free >= request)
    total = jnp.maximum(numa_total, 1)
    if strategy == STRATEGY_MOST_ALLOCATED:
        per_numa = jnp.where(
            numa_free >= request, 100 - (numa_free * 100) // total, 0
        )
    else:
        per_numa = jnp.where(numa_free >= request, (numa_free * 100) // total, 0)
    strat = jnp.max(per_numa)
    return (jnp.where(fits_single, 50, 0) + strat // 2).astype(jnp.int32)
