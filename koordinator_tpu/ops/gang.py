"""Gang (coscheduling) all-or-nothing assignment.

The reference implements gangs with a Permit-phase wait: each gang pod parks
until minMember of its gang have Reserved, then the whole gang group is
allowed to bind (``coscheduling/core/core.go:544 Permit``, ``:640
AllowGangGroup``); a timeout unreserves everything. Gang *groups* tie several
gangs together — all gangs in a group must reach minMember or none binds.

The tensor equivalent replaces park-and-wait with solve-and-rollback:

1. run the greedy batch solve (tentative Reserve for everyone),
2. count per-gang placements with a segment-sum, test ``count >= minMember``,
3. propagate failure through gang groups (a group fails if any member fails),
4. roll back every pod of a failed group — assignments, node accounting and
   quota charges — in one scatter, and
5. optionally re-solve with the freed capacity (failed gangs retry next cycle
   in the reference; extra passes here let non-gang pods reclaim capacity a
   failed gang transiently held).

PreEnqueue parity: a gang whose *pending* pod count is below minMember never
enters the solve (``core.go:212 PreEnqueue``) — its pods are masked invalid up
front.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from koordinator_tpu.ops.assignment import ScoringConfig, greedy_assign
from koordinator_tpu.quota.admission import charge_quota_batch
from koordinator_tpu.state.cluster_state import ClusterState, PodBatch


@struct.dataclass
class GangInfo:
    """Gang definitions, shape (G,). Mirrors PodGroup spec (minMember,
    gang-group annotation)."""

    min_member: jax.Array  # (G,) int32
    group_id: jax.Array    # (G,) int32 — gangs sharing a group live or die together
    valid: jax.Array       # (G,) bool

    @property
    def capacity(self) -> int:
        return self.min_member.shape[0]

    @classmethod
    def build(
        cls,
        min_member: np.ndarray,
        group_id: np.ndarray | None = None,
        capacity: int | None = None,
    ) -> "GangInfo":
        g = len(min_member)
        cap = capacity if capacity is not None else max(8, g)
        mm = np.zeros(cap, np.int32)
        mm[:g] = min_member
        gid = np.arange(cap, dtype=np.int32)
        if group_id is not None:
            gid[:g] = group_id
        valid = np.zeros(cap, bool)
        valid[:g] = True
        return cls(
            min_member=jnp.asarray(mm),
            group_id=jnp.asarray(gid),
            valid=jnp.asarray(valid),
        )


def _per_gang_counts(flags: jnp.ndarray, gang_id: jnp.ndarray, g: int) -> jnp.ndarray:
    """Sum boolean flags per gang; gang_id -1 lands in an overflow bucket."""
    gid = jnp.where(gang_id >= 0, gang_id, g)
    return jax.ops.segment_sum(flags.astype(jnp.int32), gid, num_segments=g + 1)[:g]


def _group_ok(gang_ok: jnp.ndarray, gangs: GangInfo) -> jnp.ndarray:
    """(G,) bool: True when every valid gang in the same group satisfied min."""
    g = gangs.capacity
    fails = jax.ops.segment_sum(
        (~gang_ok & gangs.valid).astype(jnp.int32), gangs.group_id, num_segments=g
    )
    return fails[gangs.group_id] == 0


def pre_enqueue_mask(pods: PodBatch, gangs: GangInfo) -> jnp.ndarray:
    """(P,) bool: gang pods are schedulable only when their gang has at least
    minMember pending pods (PreEnqueue parity)."""
    g = gangs.capacity
    pending = _per_gang_counts(pods.valid, pods.gang_id, g)
    gang_ready = pending >= gangs.min_member
    pod_gang = jnp.maximum(pods.gang_id, 0)
    return (pods.gang_id < 0) | gang_ready[pod_gang]


def rollback_failed_gangs(
    assignments: jnp.ndarray,
    state_before: ClusterState,
    pods: PodBatch,
    gangs: GangInfo,
    prior_kept: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, ClusterState, jnp.ndarray, jnp.ndarray]:
    """Undo every assignment belonging to a gang group that missed minMember.

    ``prior_kept`` (P,) marks pods already bound in earlier passes: their gang
    membership counts toward minMember (an already-permitted gang's surplus
    pods bind freely, as with the reference's Permit), but they are not
    re-assigned here.

    Returns (final_assignments, state, keep_mask, failed_mask). node_requested
    is rebuilt from state_before plus only this pass's kept pods, so rollback
    is exact; failed_mask marks pods of rolled-back gangs (they back off for
    the rest of the batch, as a failed gang waits for the next cycle upstream).
    """
    g = gangs.capacity
    assigned = (assignments >= 0) & pods.valid
    counted = assigned if prior_kept is None else (assigned | prior_kept)
    counts = _per_gang_counts(counted, pods.gang_id, g)
    gang_ok = (counts >= gangs.min_member) & gangs.valid
    ok = _group_ok(gang_ok, gangs)
    pod_gang = jnp.maximum(pods.gang_id, 0)
    keep = assigned & ((pods.gang_id < 0) | ok[pod_gang])

    final = jnp.where(keep, assignments, -1)
    node = jnp.where(keep, assignments, 0)
    add = jnp.where(keep[:, None], pods.requests, 0)
    node_requested = state_before.node_requested.at[node].add(add)
    failed = (pods.gang_id >= 0) & ~ok[pod_gang] & pods.valid
    return final, state_before.replace(node_requested=node_requested), keep, failed


def gang_assign(
    state: ClusterState,
    pods: PodBatch,
    cfg: ScoringConfig,
    gangs: GangInfo,
    quota=None,
    passes: int = 2,
    solver: str = "greedy",
    method: str = "auto",
):
    """Batch assignment with gang all-or-nothing semantics.

    Returns (assignments, state, quota) as :func:`greedy_assign` does (quota
    is None when not given). ``passes`` > 1 re-solves leftover pods after
    failed-gang rollback so freed capacity is reclaimed within the batch.

    ``solver`` picks the per-pass assignment engine: ``"greedy"`` is the
    exact sequential scan (per-pod capacity feedback, strict priority
    order); ``"batch"`` is the data-parallel propose/accept solve
    (ops/batch_assign.py) — the throughput path for large queues, with
    round-granular feedback and top-k candidate restriction. Gang
    rollback/all-or-nothing semantics are identical either way (they act
    on the assignment vector).  ``method`` passes through to the batch
    solver's candidate selection (batch_assign.CANDIDATE_METHODS), so
    gang solves can force the chunked/approx paths too.
    """
    from koordinator_tpu.ops.assignment import pod_estimates
    from koordinator_tpu.ops.batch_assign import batch_assign

    if solver not in ("greedy", "batch"):
        raise ValueError(f"unknown solver {solver!r}")
    from koordinator_tpu.ops.batch_assign import CANDIDATE_METHODS

    if method not in CANDIDATE_METHODS:
        raise ValueError(f"unknown candidate method {method!r}; "
                         f"one of {CANDIDATE_METHODS}")
    if solver == "greedy" and method != "auto":
        # the sequential scan has no candidate stage: a forced method
        # that silently did nothing would fake a measurement
        raise ValueError('method applies only to solver="batch"')

    pre_ok = pre_enqueue_mask(pods, gangs)
    active_pods = pods.replace(valid=pods.valid & pre_ok)

    total = jnp.full(pods.capacity, -1, jnp.int32)
    kept_so_far = jnp.zeros(pods.capacity, bool)
    cur_state = state
    cur_quota = quota
    # Estimated usage of pods kept in earlier passes (the reference's
    # pod-assign cache): later passes must filter/score against it, else they
    # overcommit past the load thresholds a single-pass solve would enforce.
    pod_est_all = pod_estimates(pods, cfg)
    est_accum = jnp.zeros_like(state.node_usage)

    for _ in range(passes):
        solve_state = cur_state.replace(
            node_usage=cur_state.node_usage + est_accum,
            node_agg_usage=cur_state.node_agg_usage + est_accum,
        )
        if solver == "batch":
            a, _, _ = batch_assign(solve_state, active_pods, cfg, cur_quota,
                                   method=method)
        else:
            a, _, _ = greedy_assign(solve_state, active_pods, cfg, cur_quota)

        final, cur_state, keep, failed = rollback_failed_gangs(
            a, cur_state, active_pods, gangs, prior_kept=kept_so_far
        )
        node = jnp.where(keep, final, 0)
        est_accum = est_accum.at[node].add(
            jnp.where(keep[:, None], pod_est_all, 0)
        )
        if cur_quota is not None:
            cur_quota = charge_quota_batch(
                cur_quota, active_pods.requests, active_pods.quota_id,
                keep, active_pods.non_preemptible,
            )
        total = jnp.where(keep, final, total)
        kept_so_far = kept_so_far | keep
        # next pass: still-unassigned pods stay in play, but rolled-back gangs
        # back off for the rest of the batch (retry next cycle upstream)
        active_pods = active_pods.replace(
            valid=active_pods.valid & ~keep & ~failed
        )

    return total, cur_state, cur_quota
