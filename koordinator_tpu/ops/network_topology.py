"""Network-topology-aware gang placement.

The reference packs a gang onto the network topology tree (spine/block/node
from the ClusterNetworkTopology CRD) by: computing per-node "offer slots" (how
many gang pods fit), aggregating slots/scores/existing-pod counts up the tree,
rounding slots down to per-layer pod-count multiples, picking the deepest
topology node that can hold the whole gang (preferring subtrees with existing
peer pods, then tighter fit, then score), and recursively distributing slots
(``coscheduling/core/network_topology_solver.go:53 PlacePods``, ``:239
constrainOfferSlotByPodCountMultiple``, ``:303 searchOfferSlotSatisfiedNodes``,
``:353 distributeOfferSlot``; tree built per
``frameworkext/networktopology/tree.go:43``).

TPU-native split: everything O(nodes) or O(nodes x pods) — offer-slot
computation, tree aggregation via one segment-sum over ancestor paths,
layer-multiple rounding, candidate eligibility and lexicographic ranking — is
batched JAX. The final recursive walk over the *chosen* subtree is host-side
numpy: it touches only T topology nodes (hundreds), not the N x P problem.

Tree encoding: T topology nodes across L layers (0 = cluster root, L-1 =
physical-node layer). ``topo_parent`` (T,) parent ids (root points at itself);
``node_path`` (N, L) gives every physical node's ancestor chain, so one
segment-sum of tiled per-node values aggregates the whole tree at once.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from koordinator_tpu.state.cluster_state import ClusterState, PodBatch


@struct.dataclass
class TopologyArrays:
    """Device-side encoding of the ClusterNetworkTopology tree."""

    topo_layer: jax.Array   # (T,) int32 layer index of each topology node
    topo_parent: jax.Array  # (T,) int32 parent topo id; root -> itself
    node_path: jax.Array    # (N, L) int32 ancestor topo id per layer
    node_topo: jax.Array    # (N,) int32 leaf topo id of each physical node
    topo_to_node: jax.Array  # (T,) int32 physical node index for leaf topo ids, -1 otherwise

    @property
    def num_topo(self) -> int:
        return self.topo_layer.shape[0]

    @property
    def num_layers(self) -> int:
        return self.node_path.shape[1]


@dataclasses.dataclass(frozen=True)
class TopologyRequirements:
    """Gang network requirements (mirrors JobTopologyRequirements,
    ``network_topology_types.go:33``)."""

    desired_slots: int
    must_gather_layer: int = -1       # layer index; -1 = whole cluster
    layer_multiples: tuple = ()       # (L,) pod-count multiple per layer (1 = none)


class TopologyTree:
    """Host-side tree builder: nodes join by their label path (parent->child
    layer names), as tree.AddNode derives TreeNodeMeta from node labels
    (``networktopology/tree.go:108,141``)."""

    def __init__(self, layer_names: list[str]):
        # layer_names: top-down, excluding the implicit cluster root and
        # including the node layer last, e.g. ["spine", "block", "node"].
        self.layer_names = ["cluster", *layer_names]
        self.num_layers = len(self.layer_names)
        self._index: dict[tuple[int, str], int] = {(0, ""): 0}
        self._parent = [0]
        self._layer = [0]
        self._paths: list[np.ndarray] = []
        self._leaf_topo: list[int] = []

    def add_node(self, path: list[str]) -> int:
        """Register a physical node by its label path (one name per non-root
        layer; the last entry is the node's own name). Returns node index."""
        if len(path) != self.num_layers - 1:
            raise ValueError(f"path needs {self.num_layers - 1} entries, got {len(path)}")
        parent = 0
        ids = [0]
        for depth, name in enumerate(path, start=1):
            key = (depth, name)
            tid = self._index.get(key)
            if tid is None:
                tid = len(self._parent)
                self._index[key] = tid
                self._parent.append(parent)
                self._layer.append(depth)
            ids.append(tid)
            parent = tid
        self._paths.append(np.array(ids, np.int32))
        self._leaf_topo.append(parent)
        return len(self._paths) - 1

    def build(self, capacity: int | None = None) -> TopologyArrays:
        n = len(self._paths)
        cap = capacity if capacity is not None else n
        t = len(self._parent)
        node_path = np.zeros((cap, self.num_layers), np.int32)
        if n:
            node_path[:n] = np.stack(self._paths)
        node_topo = np.zeros(cap, np.int32)
        node_topo[:n] = self._leaf_topo
        topo_to_node = np.full(t, -1, np.int32)
        for i, tid in enumerate(self._leaf_topo):
            topo_to_node[tid] = i
        return TopologyArrays(
            topo_layer=jnp.asarray(self._layer, jnp.int32),
            topo_parent=jnp.asarray(self._parent, jnp.int32),
            node_path=jnp.asarray(node_path),
            node_topo=jnp.asarray(node_topo),
            topo_to_node=jnp.asarray(topo_to_node),
        )


def gang_offer_slots(
    state: ClusterState,
    gang_requests: jnp.ndarray,
    node_valid: jnp.ndarray,
    cfg=None,
) -> jnp.ndarray:
    """(N,) int32: how many gang pods fit on each node, replacing the
    sequential filter-and-add loop (``network_topology_solver.go:113
    calculateNodeOfferSlot``) with a prefix-sum feasibility test.

    ``gang_requests`` is the (P, R) request matrix of the gang's pods (invalid
    rows zero). Slots on node n = the longest prefix of the pod list whose
    cumulative request fits the node's free capacity. When ``cfg`` (a
    ScoringConfig) is given, the k-th slot must also pass the load-aware usage
    thresholds with k pods' estimated usage added — the reference computes
    slots by running the FULL filter chain per added pod, so a plan never
    pins a pod onto a node the solver would then reject.
    """
    free = state.node_allocatable - state.node_requested  # (N, R)
    cum = jnp.cumsum(gang_requests, axis=0)  # (P, R)
    # fits[n, p] = pods[0..p] all fit on node n simultaneously
    fits = jnp.all(cum[None, :, :] <= free[:, None, :], axis=-1)
    if cfg is not None:
        from koordinator_tpu.ops import scoring
        from koordinator_tpu.ops.assignment import _threshold_mask

        est = scoring.estimate_pod_usage_by_band(
            gang_requests, cfg.estimator_factors, cfg.estimator_defaults
        )
        thr = _threshold_mask(
            cfg, state.node_usage, state.node_agg_usage,
            state.node_allocatable, jnp.cumsum(est, axis=0),
        )  # (P, N)
        fits = fits & thr.T
    prefix = jnp.cumprod(fits.astype(jnp.int32), axis=1)
    return jnp.where(node_valid, prefix.sum(axis=1), 0).astype(jnp.int32)


def aggregate_tree(
    topo: TopologyArrays,
    offer_slots: jnp.ndarray,
    node_scores: jnp.ndarray,
    node_existing: jnp.ndarray,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Sum per-physical-node values into every ancestor topology node in one
    segment-sum over the tiled (N*L,) ancestor paths
    (``network_topology_solver.go:212 evaluateTopologyNode``)."""
    t = topo.num_topo
    n = topo.node_path.shape[0]  # tree node capacity; state may be padded larger
    seg = topo.node_path.reshape(-1)  # (N*L,)

    def up(v):
        tiled = jnp.repeat(v[:n], topo.num_layers)
        return jax.ops.segment_sum(tiled, seg, num_segments=t)

    return up(offer_slots), up(node_scores), up(node_existing)


def constrain_multiples(
    topo: TopologyArrays, topo_slots: jnp.ndarray, layer_multiples: jnp.ndarray
) -> jnp.ndarray:
    """Bottom-up rounding of each topology node's slots to its layer's
    pod-count multiple (``network_topology_solver.go:249
    doConstrainOfferSlot``): a node's slots become the sum of its children's
    constrained slots, rounded down to the layer multiple."""
    t = topo.num_topo
    num_layers = layer_multiples.shape[0]
    slots = topo_slots

    def round_layer(s, layer):
        m = jnp.maximum(layer_multiples[layer], 1)
        at_layer = topo.topo_layer == layer
        return jnp.where(at_layer, (s // m) * m, s)

    # Leaf layer rounds in place; each upper layer is rebuilt from children.
    slots = round_layer(slots, num_layers - 1)
    for layer in range(num_layers - 2, -1, -1):
        child = topo.topo_layer == layer + 1
        summed = jax.ops.segment_sum(
            jnp.where(child, slots, 0), topo.topo_parent, num_segments=t
        )
        slots = jnp.where(topo.topo_layer == layer, summed, slots)
        slots = round_layer(slots, layer)
    return slots


def eligible_candidates(
    topo: TopologyArrays,
    topo_slots: jnp.ndarray,
    desired: jnp.ndarray,
    must_gather_layer: jnp.ndarray,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(ok, deepest_layer): ok marks topology nodes reachable by descending
    only through slot-satisfied ancestors from the must-gather layer
    (``network_topology_solver.go:272,303``); deepest_layer is the lowest
    layer containing any candidate — the reference keeps only the last
    (deepest) satisfied layer's candidates."""
    sat = topo_slots >= desired
    start_layer = jnp.maximum(must_gather_layer, 0)  # -1 = whole cluster
    # Descend: ok at the start layer = sat; below = sat & ok(parent).
    ok = sat & (topo.topo_layer == start_layer)
    num_layers = int(topo.node_path.shape[1])
    for _ in range(num_layers - 1):
        ok = ok | (sat & ok[topo.topo_parent] & (topo.topo_layer > start_layer))
    deepest = jnp.max(jnp.where(ok, topo.topo_layer, -1))
    return ok & (topo.topo_layer == deepest), deepest


def _ancestor_chain_keys(topo: TopologyArrays, values: jnp.ndarray) -> jnp.ndarray:
    """(T, L) matrix: values[t], values[parent(t)], ... padded with the root's
    value — the layer-by-layer comparison chain of topologyNodeLessFunc
    (``network_topology_solver.go:334``)."""
    cols = []
    cur = jnp.arange(topo.num_topo)
    for _ in range(topo.num_layers):
        cols.append(values[cur])
        cur = topo.topo_parent[cur]
    return jnp.stack(cols, axis=1)


def rank_candidates(
    topo: TopologyArrays,
    candidates: jnp.ndarray,
    topo_slots: jnp.ndarray,
    topo_scores: jnp.ndarray,
    topo_existing: jnp.ndarray,
    prefer_lower_slots: bool = True,
) -> jnp.ndarray:
    """Order candidate topology nodes by the reference's lexicographic rule:
    existing peers (desc) up the chain, then offer slots (asc when selecting a
    candidate, desc when filling children) up the chain, then score (desc),
    then id. Returns topo ids sorted best-first (non-candidates last)."""
    ex = _ancestor_chain_keys(topo, topo_existing)
    sl = _ancestor_chain_keys(topo, topo_slots)
    sc = topo_scores
    sign = 1 if prefer_lower_slots else -1
    # lexsort: last key is the primary.
    keys = [jnp.arange(topo.num_topo), -sc]
    for layer in range(topo.num_layers - 1, -1, -1):
        keys.append(sign * sl[:, layer])
    for layer in range(topo.num_layers - 1, -1, -1):
        keys.append(-ex[:, layer])
    keys.append(~candidates)  # candidates first
    return jnp.lexsort(keys)


def _distribute_host(
    topo_parent: np.ndarray,
    topo_layer: np.ndarray,
    topo_to_node: np.ndarray,
    slots: np.ndarray,
    scores: np.ndarray,
    existing: np.ndarray,
    root: int,
    desired: int,
    layer_multiples: np.ndarray,
) -> tuple[list[int], list[int]]:
    """Recursive slot distribution over the chosen subtree
    (``network_topology_solver.go:353 distributeOfferSlot``). Host-side: only
    touches the T-sized tree. Returns (ordered physical node ids, counts)."""
    t = len(topo_parent)
    children: dict[int, list[int]] = {}
    for tid in range(t):
        p = int(topo_parent[tid])
        if p != tid:
            children.setdefault(p, []).append(tid)

    def chain(tid):
        out = [tid]
        while topo_parent[out[-1]] != out[-1]:
            out.append(int(topo_parent[out[-1]]))
        return out

    def sort_key(tid):
        ch = chain(tid)
        return (
            tuple(-existing[c] for c in ch),
            tuple(-slots[c] for c in ch),  # fill higher-slot children first
            -scores[tid],
            tid,
        )

    nodes: list[int] = []
    counts: list[int] = []

    def walk(tid, want) -> int:
        layer = int(topo_layer[tid])
        mult = int(layer_multiples[layer]) if layer < len(layer_multiples) else 1
        take = min(int(slots[tid]), want)
        if mult > 1:
            take = (take // mult) * mult
        phys = int(topo_to_node[tid]) if tid < len(topo_to_node) else -1
        if phys >= 0 or tid not in children:
            if phys >= 0 and take > 0:
                nodes.append(phys)
                counts.append(take)
            return take if phys >= 0 else 0
        got = 0
        for child in sorted(children.get(tid, []), key=sort_key):
            got += walk(child, take - got)
            if got >= take:
                break
        return got

    got = walk(root, desired)
    return (nodes, counts) if got >= desired else ([], [])


def gang_candidate_prep(
    state: ClusterState,
    pods: PodBatch,
    gang_mask: np.ndarray,
    topo: TopologyArrays,
    req: TopologyRequirements,
    node_scores: jnp.ndarray | None = None,
    node_existing: jnp.ndarray | None = None,
    cfg=None,
):
    """Candidate-prep pipeline shared by BOTH gang planners (the
    baseline :func:`plan_gang_placement` and
    quality/topo_gang.plan_gang_placement_quality): whole-gang node
    feasibility intersection, desired-slots default, member-request
    front-packing, layer-multiple padding, then the offer-slots ->
    tree-aggregation -> multiples -> eligibility kernel chain.  One
    implementation, so a feasibility or multiples fix can never land
    in one planner and silently diverge the other.

    Returns ``(member_idx, desired, mults, t_slots, t_scores,
    t_existing, cand)``; only candidate ORDER and the commit rule
    differ between planners downstream.
    """
    n = state.capacity
    node_valid = state.node_valid
    if node_scores is None:
        node_scores = jnp.zeros(n, jnp.int32)
    if node_existing is None:
        node_existing = jnp.zeros(n, jnp.int32)

    gang_mask = np.asarray(gang_mask)
    member_idx = np.flatnonzero(gang_mask)
    # Per-pod feasibility (affinity etc.) applies to the whole gang: a node
    # any member cannot use offers no slots to the gather plan.
    if member_idx.size:
        node_valid = node_valid & jnp.all(
            pods.feasible_rows(state)[jnp.asarray(member_idx)], axis=0
        )
    desired = req.desired_slots if req.desired_slots > 0 else len(member_idx)
    gang_requests = jnp.where(
        jnp.asarray(gang_mask)[:, None], pods.requests, 0
    )
    # Pack member requests to the front so the prefix test sees them contiguously.
    order = np.argsort(~gang_mask, kind="stable")
    gang_requests = gang_requests[jnp.asarray(order)]

    mults = jnp.asarray(
        np.pad(
            np.asarray(req.layer_multiples or (), np.int32),
            (0, topo.num_layers - len(req.layer_multiples or ())),
            constant_values=1,
        )
    )

    slots = gang_offer_slots(state, gang_requests, node_valid, cfg)
    t_slots, t_scores, t_existing = aggregate_tree(
        topo, slots, node_scores, node_existing)
    t_slots = constrain_multiples(topo, t_slots, mults)
    cand, _ = eligible_candidates(
        topo, t_slots, jnp.int32(desired), jnp.int32(req.must_gather_layer)
    )
    return member_idx, desired, mults, t_slots, t_scores, t_existing, cand


def plan_gang_placement(
    state: ClusterState,
    pods: PodBatch,
    gang_mask: np.ndarray,
    topo: TopologyArrays,
    req: TopologyRequirements,
    node_scores: jnp.ndarray | None = None,
    node_existing: jnp.ndarray | None = None,
    cfg=None,
) -> np.ndarray:
    """Full placement plan for one gang: (P,) int32 planned node per gang pod
    (-1 for non-members / infeasible). Mirrors PlacePods
    (``network_topology_solver.go:53``): the plan is then fed to the solver
    one node at a time (the reference's FindOneNode path).
    """
    member_idx, desired, mults, t_slots, t_scores, t_existing, cand = (
        gang_candidate_prep(state, pods, gang_mask, topo, req,
                            node_scores, node_existing, cfg))
    ranked = rank_candidates(topo, cand, t_slots, t_scores, t_existing)

    # Host-side: walk ranked candidates until one distributes fully.
    cand_np = np.asarray(cand)
    plan = np.full(pods.capacity, -1, np.int32)
    if not cand_np.any():
        return plan
    parent_np = np.asarray(topo.topo_parent)
    layer_np = np.asarray(topo.topo_layer)
    t2n = np.asarray(topo.topo_to_node)
    slots_np = np.asarray(t_slots)
    scores_np = np.asarray(t_scores)
    exist_np = np.asarray(t_existing)
    mults_np = np.asarray(mults)
    for tid in np.asarray(ranked):
        if not cand_np[tid]:
            break
        nodes, counts = _distribute_host(
            parent_np, layer_np, t2n, slots_np, scores_np, exist_np,
            int(tid), desired, mults_np,
        )
        if nodes:
            flat = np.repeat(nodes, counts)[: len(member_idx)]
            plan[member_idx[: len(flat)]] = flat
            return plan
    return plan
