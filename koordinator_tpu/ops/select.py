"""Shared top-k selection idiom for allocation kernels.

Both the cpuset accumulator (ops/numa.take_cpus) and the device allocator
(ops/deviceshare.allocate_on_node) reduce to: order candidates by a
lexicographic priority, take the first k eligible. One helper so the idiom
has a single definition.
"""

from __future__ import annotations

import jax.numpy as jnp


def take_by_rank(
    keys: tuple,            # lexsort keys, LAST key is the primary
    eligible: jnp.ndarray,  # (C,) bool
    k: jnp.ndarray,         # () int32 — how many to take
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Returns ((C,) bool selection, ok). Selection is empty unless at least
    k eligible candidates exist."""
    c = eligible.shape[0]
    order = jnp.lexsort(keys)
    rank = jnp.empty(c, jnp.int32).at[order].set(jnp.arange(c, dtype=jnp.int32))
    selected = (rank < k) & eligible
    ok = jnp.sum(selected.astype(jnp.int32)) >= k
    return selected & ok, ok
