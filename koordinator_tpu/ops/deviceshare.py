"""Device-share scheduling kernels: GPU/RDMA fit, scoring, allocation.

TPU-native equivalent of the reference's deviceshare plugin
(pkg/scheduler/plugins/deviceshare/: device_cache.go nodeDevice state,
device_allocator.go AutopilotAllocator + tryJointAllocate, allocator_gpu.go,
gpu_shared_resource_templates_cache.go partition templates, scoring.go).

Resource model (apis/extension/device_share.go): a device exposes
``core`` in percent-of-device units (100 = one whole device — the reference's
koordinator.sh/gpu-core) and ``memory`` in MiB. A request is either

- **shared**: core < 100 — lands on ONE device with enough free core+memory, or
- **whole**: core = n*100 — takes n fully-free devices (multi-device requests
  cannot split a device, matching ValidateDeviceRequest).

Cluster-wide device state is a (nodes x max-devices x 2) tensor per device
type; Filter/Score are batched over all nodes, allocation picks device ids on
the chosen node (same batched-filter / single-node-reserve split as
ops/numa.py). Joint GPU+NIC allocation prefers devices of both types in one
topology group (device_allocator.go:208 tryJointAllocate).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from koordinator_tpu.ops.select import take_by_rank
from koordinator_tpu.state.cluster_state import _bucket

#: Per-device resource dims: core (percent, 100 per device) and memory (MiB).
DEV_CORE = 0
DEV_MEM = 1
NUM_DEV_DIMS = 2

#: Scheduler-facing allocate strategies (DeviceShareArgs scoring strategy).
DEV_BINPACK = 0   # most-allocated: fill busy devices/nodes first
DEV_SPREAD = 1    # least-allocated


@struct.dataclass
class DeviceState:
    """One device type (GPU, RDMA, ...) across the cluster, padded (N, D)."""

    total: jax.Array    # (N, D, 2) int32 per-device capacity
    free: jax.Array     # (N, D, 2) int32 unallocated
    valid: jax.Array    # (N, D) bool — device exists
    healthy: jax.Array  # (N, D) bool — Device CRD health
    group: jax.Array    # (N, D) int32 topology group (PCIe/NUMA) for joint alloc

    @property
    def shape(self) -> tuple[int, int]:
        return self.valid.shape

    @classmethod
    def zeros(cls, nodes: int, devices: int = 16) -> "DeviceState":
        return cls(
            total=jnp.zeros((nodes, devices, NUM_DEV_DIMS), jnp.int32),
            free=jnp.zeros((nodes, devices, NUM_DEV_DIMS), jnp.int32),
            valid=jnp.zeros((nodes, devices), bool),
            healthy=jnp.zeros((nodes, devices), bool),
            group=jnp.zeros((nodes, devices), jnp.int32),
        )

    @classmethod
    def build(
        cls,
        per_node_devices: list[list[dict]],
        node_capacity: int | None = None,
        device_capacity: int | None = None,
    ) -> "DeviceState":
        """From host records: one dict per device with keys
        core/memory/group/healthy (Device CRD device_types.go:112 entries)."""
        n = len(per_node_devices)
        ncap = node_capacity or _bucket(max(n, 1))
        dmax = max((len(d) for d in per_node_devices), default=1)
        dcap = device_capacity or _bucket(max(dmax, 1), minimum=8)
        total = np.zeros((ncap, dcap, NUM_DEV_DIMS), np.int32)
        valid = np.zeros((ncap, dcap), bool)
        healthy = np.zeros((ncap, dcap), bool)
        group = np.zeros((ncap, dcap), np.int32)
        for i, devs in enumerate(per_node_devices):
            for j, d in enumerate(devs):
                total[i, j, DEV_CORE] = d.get("core", 100)
                total[i, j, DEV_MEM] = d.get("memory", 0)
                valid[i, j] = True
                healthy[i, j] = d.get("healthy", True)
                group[i, j] = d.get("group", 0)
        return cls(
            total=jnp.asarray(total),
            free=jnp.asarray(total.copy()),
            valid=jnp.asarray(valid),
            healthy=jnp.asarray(healthy),
            group=jnp.asarray(group),
        )


def split_request(core: int, memory: int) -> tuple[int, int, int]:
    """(n_whole, per_device_core, per_device_memory) — ValidateDeviceRequest.

    core=350 is invalid in the reference (multi-device must be whole); we
    round it up to 4 whole devices to stay total-capacity-safe.
    """
    if core <= 100:
        return (0, core, memory)
    n = -(-core // 100)
    return (n, 100, -(-memory // n) if memory else 0)


def _usable(dev: DeviceState) -> jnp.ndarray:
    return dev.valid & dev.healthy


def _whole_free(dev: DeviceState) -> jnp.ndarray:
    """(N, D) bool — device is fully unallocated."""
    return _usable(dev) & jnp.all(dev.free == dev.total, axis=-1)


def device_fit(
    dev: DeviceState,
    n_whole: jnp.ndarray,   # () int32, 0 = shared request
    core: jnp.ndarray,      # () per-device core ask
    memory: jnp.ndarray,    # () per-device memory ask
) -> jnp.ndarray:
    """(N,) bool — batched Filter over all nodes."""
    fits_each = (
        _usable(dev)
        & (dev.free[..., DEV_CORE] >= core)
        & (dev.free[..., DEV_MEM] >= memory)
    )
    shared_ok = jnp.any(fits_each, axis=-1)
    # whole devices must also cover the per-device ask (a fully-free device
    # with less memory than asked is not a fit)
    whole_capable = (
        _whole_free(dev)
        & (dev.total[..., DEV_CORE] >= core)
        & (dev.total[..., DEV_MEM] >= memory)
    )
    whole_ok = jnp.sum(whole_capable.astype(jnp.int32), axis=-1) >= n_whole
    return jnp.where(n_whole > 0, whole_ok, shared_ok)


def device_score(
    dev: DeviceState,
    n_whole: jnp.ndarray,
    core: jnp.ndarray,
    memory: jnp.ndarray,
    strategy: int = DEV_BINPACK,
) -> jnp.ndarray:
    """(N,) int32 in [0, 100] — scoring.go's most/least-allocated over the
    node's device pool (utilization after placing the request)."""
    total = jnp.maximum(jnp.sum(jnp.where(dev.valid[..., None], dev.total, 0),
                                axis=1), 1)                    # (N, 2)
    used = total - jnp.sum(jnp.where(dev.valid[..., None], dev.free, 0), axis=1)
    ask_core = jnp.where(n_whole > 0, n_whole * 100, core)
    ask = jnp.stack([ask_core, jnp.where(n_whole > 0, n_whole * memory, memory)])
    util = jnp.clip((used + ask[None, :]) * 100 // total, 0, 100)  # (N, 2)
    score = jnp.sum(util, axis=-1) // NUM_DEV_DIMS
    if strategy == DEV_BINPACK:
        return score.astype(jnp.int32)
    return (100 - score).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("strategy",))
def allocate_on_node(
    dev: DeviceState,
    node: jnp.ndarray,       # () int32 chosen node row
    n_whole: jnp.ndarray,
    core: jnp.ndarray,
    memory: jnp.ndarray,
    strategy: int = DEV_BINPACK,
    prefer_group: jnp.ndarray | None = None,  # () int32, -1 = no preference
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Pick device ids on one node: returns ((D,) bool selection, ok).

    Shared: best-fit — the fitting device with the least free core (binpack)
    or most free (spread). Whole: n fully-free devices, preferring the
    requested topology group, then group-crowding order (keeps big groups
    intact, the allocator's honor-device-topology behavior).
    """
    d = dev.valid.shape[1]
    free = dev.free[node]            # (D, 2)
    total = dev.total[node]
    usable = dev.valid[node] & dev.healthy[node]
    groups = dev.group[node]

    in_group = (
        (groups == prefer_group) & (prefer_group >= 0)
        if prefer_group is not None
        else jnp.zeros(d, bool)
    )

    # -- shared single-device path: best-fit within the preferred topology
    # group first, then any group (same-group-then-fallback, tryJointAllocate)
    fits = usable & (free[:, DEV_CORE] >= core) & (free[:, DEV_MEM] >= memory)
    fit_key = free[:, DEV_CORE] if strategy == DEV_BINPACK else -free[:, DEV_CORE]
    shared_sel, shared_ok = take_by_rank(
        (jnp.arange(d), fit_key, ~in_group, ~fits), fits, jnp.int32(1)
    )

    # -- whole-devices path (per-device capacity must cover the ask)
    wfree = (
        usable
        & jnp.all(free == total, axis=-1)
        & (total[:, DEV_CORE] >= core)
        & (total[:, DEV_MEM] >= memory)
    )
    # group crowding: how many whole-free devices share my group (take from
    # the group that can satisfy the request with least leftover)
    grp_count = jax.ops.segment_sum(
        wfree.astype(jnp.int32), jnp.clip(groups, 0), d
    )[jnp.clip(groups, 0)]
    can_satisfy = grp_count >= n_whole
    whole_sel, whole_ok = take_by_rank(
        (
            jnp.arange(d),
            jnp.where(can_satisfy, grp_count, jnp.iinfo(jnp.int32).max),
            ~in_group,
            ~wfree,
        ),
        wfree,
        n_whole,
    )

    sel = jnp.where(n_whole > 0, whole_sel, shared_sel)
    ok = jnp.where(n_whole > 0, whole_ok, shared_ok)
    return sel & ok, ok


def commit_allocation(
    dev: DeviceState,
    node: jnp.ndarray,
    selection: jnp.ndarray,  # (D,) bool
    core: jnp.ndarray,
    memory: jnp.ndarray,
) -> DeviceState:
    """Subtract the per-device ask from the selected devices' free."""
    ask = jnp.stack([core, memory]).astype(jnp.int32)
    delta = selection[:, None] * ask[None, :]
    return dev.replace(free=dev.free.at[node].add(-delta))


def release_allocation(
    dev: DeviceState,
    node: jnp.ndarray,
    selection: jnp.ndarray,
    core: jnp.ndarray,
    memory: jnp.ndarray,
) -> DeviceState:
    ask = jnp.stack([core, memory]).astype(jnp.int32)
    delta = selection[:, None] * ask[None, :]
    return dev.replace(free=dev.free.at[node].add(delta))


@functools.partial(jax.jit, static_argnames=("strategy", "nic_required"))
def joint_allocate(
    gpu: DeviceState,
    nic: DeviceState,
    node: jnp.ndarray,
    n_whole: jnp.ndarray,
    core: jnp.ndarray,
    memory: jnp.ndarray,
    nic_core: jnp.ndarray,
    nic_memory: jnp.ndarray,
    strategy: int = DEV_BINPACK,
    nic_required: bool = False,
):
    """GPU + NIC co-allocation on one node (tryJointAllocate semantics).

    Allocates GPUs first, then a NIC in the same topology group as the chosen
    GPUs; if no same-group NIC fits, falls back to any NIC (or fails when
    ``nic_required``, the JointAllocate required-scope behavior).

    Returns (gpu_sel, nic_sel, ok).
    """
    gpu_sel, gpu_ok = allocate_on_node(
        gpu, node, n_whole, core, memory, strategy=strategy
    )
    # majority group of the selected gpus (first selected device's group)
    first = jnp.argmax(gpu_sel)
    gpu_group = jnp.where(gpu_ok, gpu.group[node][first], -1)

    nic_sel, nic_ok = allocate_on_node(
        nic, node, jnp.int32(0), nic_core, nic_memory,
        strategy=strategy, prefer_group=gpu_group,
    )
    # required mode: the NIC AND every selected GPU must share one group
    # (a multi-group GPU spread has no single group for the NIC to sit in)
    nic_same_group = jnp.any(nic_sel & (nic.group[node] == gpu_group))
    gpus_one_group = jnp.all(~gpu_sel | (gpu.group[node] == gpu_group))
    if nic_required:
        nic_ok = nic_ok & nic_same_group & gpus_one_group
    ok = gpu_ok & nic_ok
    return gpu_sel & ok, nic_sel & ok, ok


def partition_allocate(
    dev: DeviceState,
    node: jnp.ndarray,
    templates: jnp.ndarray,   # (T, D) bool — allowed whole-device partitions
    n_whole: jnp.ndarray,     # () devices wanted
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Pick a partition-template-conforming whole-device set (GPU partition
    tables, gpu_shared_resource_templates_cache.go): the selection must be an
    exact template row whose devices are all free; earlier rows win (the
    table's preference order)."""
    wfree = _whole_free(dev)[node]                         # (D,)
    sizes = jnp.sum(templates.astype(jnp.int32), axis=-1)  # (T,)
    fits = (
        (sizes == n_whole)
        & jnp.all(~templates | wfree[None, :], axis=-1)
    )
    pick = jnp.argmax(fits)                                # first fitting row
    ok = jnp.any(fits)
    return templates[pick] & ok, ok
