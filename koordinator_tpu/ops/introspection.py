"""JAX solver introspection: recompiles, device bytes, profiler capture.

Three answers a self-observing scheduler needs when the latency SLO
burns (slo_monitor.py) — is it recompiles, device memory pressure, or
something else:

- :func:`instrument` wraps a jitted entry point and counts jit-cache
  misses (a trace+compile happened) per shape bucket into
  ``solver_recompiles_total{fn, shape}`` plus a live
  ``solver_jit_cache_size{fn}`` gauge.  The power-of-two bucketing in
  state/cluster_state bounds compiles to O(log N) over cluster life; a
  nonzero steady-state recompile RATE is exactly the regression the
  incremental-solve design must catch, not assume away.
- :func:`device_bytes` sums the device-resident footprint of any pytree
  (``ClusterState``, ``CandidateCache``) from array metadata — no
  transfer, no sync.
- :class:`ProfilerCapture` exposes ``jax.profiler`` start/stop as an
  on-demand, **gated-off-by-default** capture for the
  ``/debug/profile?seconds=N`` endpoint (a production scheduler must
  not let any caller start a device trace unless the operator enabled
  the gate at assembly).
"""

from __future__ import annotations

import math
import re
import tempfile
import threading
import time

from koordinator_tpu import metrics


def default_shape_of(args, kwargs) -> str:
    """Fallback shape-bucket label: the distinct leaf shapes of the
    positional args, largest first, capped for label sanity."""
    import jax

    shapes = set()
    for leaf in jax.tree.leaves(args):
        shape = getattr(leaf, "shape", None)
        if shape is not None:
            shapes.add(tuple(int(d) for d in shape))
    top = sorted(shapes,
                 key=lambda s: (-int(math.prod(s or (1,))), s))[:3]
    return "/".join("x".join(map(str, s)) if s else "scalar" for s in top)


class InstrumentedJit:
    """Callable wrapper over a jitted function that observes its jit
    cache: a call that grows the cache was a miss (trace+compile), and
    the miss is attributed to the caller-derived shape bucket.

    The wrapper is pass-through — donation, static args, and outputs
    behave exactly as on the wrapped function.  When the runtime does
    not expose a cache-size probe the wrapper degrades to a plain
    forward (counting nothing, costing one attribute check).
    """

    def __init__(self, fn, name: str, shape_of=None):
        self.fn = fn
        self.name = name
        self.shape_of = shape_of or default_shape_of
        self._probe = getattr(fn, "_cache_size", None)
        self.misses = 0

    def _cache_size(self) -> int | None:
        if self._probe is None:
            return None
        try:
            return int(self._probe())
        except Exception:  # noqa: BLE001 — probe is best-effort
            return None

    def __call__(self, *args, **kwargs):
        before = self._cache_size()
        out = self.fn(*args, **kwargs)
        after = self._cache_size()
        if before is not None and after is not None and after > before:
            try:
                shape = self.shape_of(args, kwargs)
            except Exception:  # noqa: BLE001 — labeling must not fail a solve
                shape = "unknown"
            self.misses += after - before
            metrics.solver_recompiles.inc(
                after - before, labels={"fn": self.name, "shape": shape})
            metrics.solver_jit_cache_size.set(
                float(after), labels={"fn": self.name})
        return out


def instrument(fn, name: str, shape_of=None) -> InstrumentedJit:
    """Wrap a jitted entry point for recompile accounting.

    ``shape_of(args, kwargs) -> str`` names the shape bucket; callers
    with a known signature should pass one (e.g. ``P{batch}xN{nodes}``)
    — the default derives a generic label from leaf shapes."""
    return InstrumentedJit(fn, name, shape_of=shape_of)


def device_bytes(tree) -> int:
    """Total ``nbytes`` of the array leaves of a pytree (0 for None).
    Metadata-only: never blocks on or transfers device buffers."""
    if tree is None:
        return 0
    import jax

    total = 0
    for leaf in jax.tree.leaves(tree):
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is not None:
            total += int(nbytes)
    return total


def device_bytes_by_shard(tree) -> dict[int, int]:
    """Per-device footprint of a pytree's arrays: {device_id: bytes}.

    Sums each leaf's addressable shards by the device they live on —
    node-axis-sharded solver tensors report one slice per device, while
    replicated leaves honestly charge EVERY device a full copy (that is
    what replication costs in HBM).  Metadata-only like
    :func:`device_bytes`; single-device arrays land on their device's id.
    """
    if tree is None:
        return {}
    import jax

    out: dict[int, int] = {}
    for leaf in jax.tree.leaves(tree):
        shards = getattr(leaf, "addressable_shards", None)
        if shards:
            for sh in shards:
                nbytes = getattr(sh.data, "nbytes", None)
                if nbytes is not None:
                    did = int(sh.device.id)
                    out[did] = out.get(did, 0) + int(nbytes)
        else:
            nbytes = getattr(leaf, "nbytes", None)
            if nbytes is not None:
                out[0] = out.get(0, 0) + int(nbytes)
    return out


def device_bytes_by_mesh_shard(tree, mesh) -> dict[tuple[int, int], int]:
    """Per-(pod_shard, node_shard) footprint of a pytree's arrays over a
    2-D solver mesh: {(pi, ni): bytes}.

    The same metadata-only walk as :func:`device_bytes_by_shard`, with
    device ids mapped to their mesh coordinates so a lopsided tile —
    the placement bug class of the 2-D layout — reads directly off the
    (pods, nodes) grid instead of a flat device list.  Devices outside
    the mesh (host-resident spill) land under ``(-1, -1)``."""
    if tree is None or mesh is None:
        return {}
    from koordinator_tpu.parallel.mesh import NODES_AXIS, PODS_AXIS

    import numpy as np

    coord_of: dict[int, tuple[int, int]] = {}
    grid = np.asarray(mesh.devices)
    axes = list(mesh.axis_names)
    pi_ax, ni_ax = axes.index(PODS_AXIS), axes.index(NODES_AXIS)
    for idx, dev in np.ndenumerate(grid):
        coord_of[int(dev.id)] = (int(idx[pi_ax]), int(idx[ni_ax]))
    out: dict[tuple[int, int], int] = {}
    for did, nbytes in device_bytes_by_shard(tree).items():
        key = coord_of.get(int(did), (-1, -1))
        out[key] = out.get(key, 0) + int(nbytes)
    return out


#: HLO collective op mnemonics counted by :func:`collective_counts`
_COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter",
                   "collective-permute", "all-to-all")


def collective_counts(compiled_text: str) -> dict[str, int]:
    """Count collective ops in compiled HLO text — the communication
    profile of a sharded solve (``jit(fn).lower(*args).compile()
    .as_text()``).  Returns {op: count} for the ops that appear."""
    out: dict[str, int] = {}
    for line in compiled_text.splitlines():
        stripped = line.lstrip()
        # HLO spells an op as "%name = type op-name(...)" (with -start/
        # -done pairs for async forms; count the starts only)
        for op in _COLLECTIVE_OPS:
            if (f" {op}(" in stripped or f" {op}-start(" in stripped
                    or stripped.startswith((f"{op}(", f"{op}-start("))):
                out[op] = out.get(op, 0) + 1
    return out


def compiled_collectives(jitted, *args, **kwargs) -> dict[str, int]:
    """Lower+compile a jitted callable against example args and report
    its collective-op counts (one AOT compile; the result is cached by
    the jit, so a subsequent real call does not recompile)."""
    compiled = jitted.lower(*args, **kwargs).compile()
    return collective_counts(compiled.as_text())


_REPLICA_GROUP_RE = re.compile(r"replica_groups=\{\{([0-9, ]+)\}")


def collective_axis_counts(compiled_text: str, mesh) -> dict[str, dict]:
    """Collective-op counts PER MESH AXIS: {axis: {op: count}}.

    Classifies each collective in the compiled HLO by its first replica
    group's size against the 2-D mesh's axis sizes — a nodes-axis psum
    groups ``dn`` devices, a pods-axis gather ``dp``, a whole-mesh
    reduction ``dp*dn`` (reported as ``"global"``).  Sizes matching
    neither (or an op with no parsable groups) land under ``"other"``;
    when the two axes are the same size the split is ambiguous and both
    axes' ops land under ``"pods_or_nodes"``.  A text-level heuristic —
    the only stable surface without a compiler API — good enough to put
    the communication profile of a sharded solve next to its wall time
    in the bench record."""
    if mesh is None:
        return {}
    from koordinator_tpu.parallel.mesh import (
        nodes_shard_count,
        pods_shard_count,
    )

    dp, dn = pods_shard_count(mesh), nodes_shard_count(mesh)
    by_size = {dp * dn: "global"}
    if dp == dn:
        by_size[dn] = "pods_or_nodes"
    else:
        by_size.update({dn: "nodes", dp: "pods"})
    out: dict[str, dict] = {}
    for line in compiled_text.splitlines():
        stripped = line.lstrip()
        for op in _COLLECTIVE_OPS:
            if not (f" {op}(" in stripped or f" {op}-start(" in stripped
                    or stripped.startswith((f"{op}(", f"{op}-start("))):
                continue
            m = _REPLICA_GROUP_RE.search(stripped)
            axis = "other"
            if m is not None:
                size = len([t for t in m.group(1).split(",") if t.strip()])
                axis = by_size.get(size, "other")
            out.setdefault(axis, {})
            out[axis][op] = out[axis].get(op, 0) + 1
    return out


class ProfileDisabled(Exception):
    """The profiling endpoint gate is off (the default)."""


class ProfileBusy(Exception):
    """A capture is already in flight (jax allows one trace at a time)."""


class ProfilerCapture:
    """On-demand ``jax.profiler`` trace capture behind an explicit gate.

    ``enabled=False`` (the default) refuses every capture with
    :class:`ProfileDisabled` — the endpoint ships dark and an operator
    turns it on at assembly (``--enable-profile-endpoint``).  Captures
    serialize on a lock and are clamped to ``max_seconds``.
    ``profiler``/``sleep`` are injectable for tests.
    """

    def __init__(self, enabled: bool = False, out_dir: str | None = None,
                 max_seconds: float = 30.0, profiler=None, sleep=time.sleep):
        self.enabled = enabled
        self.out_dir = out_dir
        self.max_seconds = max_seconds
        self._profiler = profiler
        self._sleep = sleep
        self._lock = threading.Lock()
        self.captures = 0

    def _jax_profiler(self):
        if self._profiler is not None:
            return self._profiler
        import jax.profiler

        return jax.profiler

    def capture(self, seconds: float) -> dict:
        """Run one trace for ``seconds`` (clamped to (0, max_seconds]);
        returns ``{"dir", "seconds"}`` where ``dir`` holds the
        TensorBoard-loadable trace."""
        if not self.enabled:
            raise ProfileDisabled(
                "profiling endpoint disabled (enable at assembly with "
                "--enable-profile-endpoint)")
        seconds = float(seconds)
        if not math.isfinite(seconds):
            # nan survives min/max clamping and would start a trace
            # only to die inside sleep()
            raise ValueError("seconds must be finite")
        seconds = min(max(seconds, 0.001), self.max_seconds)
        if not self._lock.acquire(blocking=False):
            raise ProfileBusy("a profiler capture is already running")
        try:
            out_dir = self.out_dir or tempfile.mkdtemp(
                prefix="koord-jax-profile-")
            profiler = self._jax_profiler()
            profiler.start_trace(out_dir)
            try:
                self._sleep(seconds)
            finally:
                profiler.stop_trace()
            self.captures += 1
            return {"dir": out_dir, "seconds": seconds}
        finally:
            self._lock.release()
