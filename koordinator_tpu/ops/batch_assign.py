"""Batch-parallel assignment: propose/accept rounds instead of an O(P) scan.

``greedy_assign`` (ops/assignment.py) is the exact sequential solver — one
``lax.scan`` step per pod, 50k dependent steps at the north-star shape.  This
module is the throughput path: the whole pending queue lands in a handful of
data-parallel rounds.

    1. ONE fused Filter+Score pass over the (P, N) problem (same kernels as
       ``score_pods``), with a per-pod rotated tie-break so identical pods
       spread over equal-scored nodes instead of stampeding one argmax;
    2. ``lax.top_k`` -> each pod's k best candidate nodes, (P, k);
    3. K propose/accept rounds on the small (P, k) tensors: every active pod
       proposes its best candidate that still fits, conflicts are resolved by
       a segmented prefix-sum over requests in priority order (higher-priority
       pods win a contended node, exactly one device-wide sort per round), and
       elastic-quota headroom is enforced by the same prefix trick per
       ancestor level of the quota chain.

Semantics vs the reference / greedy_assign:
- priority order in conflicts matches the scheduler queue order
  (priority desc, stable) — the prefix acceptance is the tensor analog of
  higher-priority pods going through scheduleOne first;
- capacity and quota feedback happen per round (snapshot granularity) rather
  than per pod: scores are not recomputed between two pods of the same round,
  like the upstream parallel Filter/Score over one snapshot;
- a pod only ever considers its top-k candidates; under extreme contention a
  pod can go unassigned in this solve even though some node below its top-k
  would fit (it retries next scheduler round).  k and the round count bound
  the approximation.

Reference parity anchors: scoring pipeline per cmd/koord-scheduler/main.go
plugin registry; quota admission per elasticquota/plugin.go:256-304; the
conflict rule mirrors upstream queue ordering (priority, then FIFO).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from koordinator_tpu.ops.assignment import ScoringConfig, score_pods
from koordinator_tpu.quota.admission import (
    QuotaDeviceState,
    charge_quota_batch,
    quota_admission_mask,
)
from koordinator_tpu.state.cluster_state import ClusterState, PodBatch

#: tie-break field width: node index occupies the low bits of the ranking key
_TB_BITS = 15  # supports node capacities up to 32768
_SCORE_CLIP = (1 << 30 - _TB_BITS) - 1

#: hard node-capacity ceiling of the int32 ranking key: the rotated node
#: index must fit _TB_BITS low bits or it aliases into the score field and
#: candidates silently mis-rank.  Shapes are static under jit, so this is
#: enforced at trace time — a 40k-node problem fails loudly instead.
MAX_NODE_CAPACITY = 1 << _TB_BITS


def check_node_capacity(n: int) -> None:
    """Raise if a node capacity exceeds the ranking key's ceiling."""
    if n > MAX_NODE_CAPACITY:
        raise ValueError(
            f"node capacity {n} exceeds the batched solver's ranking-key "
            f"ceiling of {MAX_NODE_CAPACITY} (= 2**{_TB_BITS}): the rotated "
            "node index would alias into the score bits and mis-rank "
            "candidates.  Mesh sharding does not help — shapes stay global "
            "under GSPMD.  Partition the cluster into <=32768-node node "
            "pools solved independently, or widen the packing to a 64-bit "
            "key (_TB_BITS) off-TPU.")


def _ranked_scores(
    scores: jnp.ndarray, feasible: jnp.ndarray, spread_bits: int = 0,
    row_offset=0,
) -> jnp.ndarray:
    """(P, N) int32 ranking key: score in the high bits, a per-pod rotated
    node index in the low bits.  Equal-scored nodes order differently for
    every pod, so homogeneous pods fan out instead of all picking node 0
    (selectHost randomizes among maxima upstream; rotation is the
    deterministic equivalent).

    ``spread_bits`` quantizes the score into buckets of ``2**spread_bits``
    before ranking.  With exact scores, every pod ranks nodes near-identically
    and the whole queue's top-k candidate sets collapse onto the same few
    nodes — at 50k pods x 10k nodes that strands >90% of a schedulable queue.
    Bucketing widens the tie groups so the rotation fans candidates over ALL
    near-best nodes; the score sacrifice is bounded by the bucket width
    (upstream's selectHost already treats equal-enough scores as
    interchangeable: defaultPodTopologySpread jitter, selectHost randomness).
    """
    p, n = scores.shape
    check_node_capacity(n)
    # per-pod offset; row_offset keeps chunked reductions rotating by the
    # GLOBAL pod index, so chunking never changes any pod's candidates
    rot = ((jnp.arange(p, dtype=jnp.int32) + row_offset) * 7919)[:, None]
    tb = (jnp.arange(n, dtype=jnp.int32)[None, :] - rot) % n
    # invert so the SMALLEST rotated distance ranks highest among ties
    tb = (n - 1) - tb
    q = jnp.clip(scores, 0, _SCORE_CLIP) >> spread_bits
    key = (q << _TB_BITS) | tb
    return jnp.where(feasible, key, -1)


def _prefix_accept(
    choice: jnp.ndarray,     # (P,) int32 proposed segment (node/quota row)
    requests: jnp.ndarray,   # (P, R) int32
    free: jnp.ndarray,       # (S, R) int32 segment headroom
    order: jnp.ndarray,      # (P,) priority-descending pod order
    active: jnp.ndarray,     # (P,) bool — proposers this round
) -> jnp.ndarray:
    """(P,) bool: cumulative request per segment (taken in ``order`` among
    active proposers) fits the segment's headroom, counting the pod itself.

    This is the round's conflict resolution: the tensor equivalent of
    higher-priority pods passing through the scheduling cycle first.

    Fast path: when NO segment is oversubscribed (every segment's total
    proposed request fits its headroom — the common case from round 2 on,
    once the first round's land grab settles), every active proposer's
    prefix trivially fits, so the answer is ``active`` and the device-wide
    stable sort is skipped via ``lax.cond``.  One cheap segment-sum pays
    for the detection; the sorted path below remains the general case and
    the single source of truth for contended rounds.
    """
    p, r = requests.shape
    s = free.shape[0]
    seg = jnp.where(active, choice, s)            # inactive -> overflow row
    req_act = jnp.where(active[:, None], requests, 0)
    totals = jax.ops.segment_sum(req_act, seg, num_segments=s + 1)[:s]
    has_prop = (
        jax.ops.segment_sum(active.astype(jnp.int32), seg,
                            num_segments=s + 1)[:s] > 0
    )
    contended = jnp.any(has_prop[:, None] & (totals > free))

    def fast(_):
        # total per segment fits => every within-segment prefix fits
        return active

    def slow(_):
        return _prefix_accept_sorted(seg, requests, free, order, active)

    return jax.lax.cond(contended, slow, fast, None)


def _prefix_accept_sorted(seg, requests, free, order, active):
    """The general contended-round path: stable sort groups segments in
    priority order, a segmented prefix-sum checks cumulative fit."""
    p, r = requests.shape
    seg_o = seg[order]
    req_o = jnp.where(active[order][:, None], requests[order], 0)
    pos = jnp.argsort(seg_o, stable=True)         # group segments, keep order
    seg_s = seg_o[pos]
    req_s = req_o[pos]
    cum = jnp.cumsum(req_s, axis=0)
    excl = cum - req_s
    is_start = jnp.concatenate(
        [jnp.ones((1,), bool), seg_s[1:] != seg_s[:-1]]
    )
    # propagate each segment's starting cumulative value (cum is
    # non-decreasing, so a running max of start markers yields the most
    # recent segment start)
    base = jax.lax.associative_scan(
        jnp.maximum, jnp.where(is_start[:, None], excl, -1), axis=0
    )
    prefix = cum - base                           # within-segment incl. self
    free_pad = jnp.concatenate([free, jnp.zeros((1, r), free.dtype)])
    fits = jnp.all((prefix <= free_pad[seg_s]) | (req_s == 0), axis=-1)
    out = jnp.zeros(p, bool).at[order[pos]].set(fits)
    return out & active


def _quota_prefix_accept(
    quota: QuotaDeviceState,
    requests: jnp.ndarray,
    pods: PodBatch,
    order: jnp.ndarray,
    active: jnp.ndarray,
) -> jnp.ndarray:
    """(P,) bool: within-round quota headroom conflict resolution.

    For every ancestor level of the quota chain, the cumulative masked
    request of this round's proposers must fit the ancestor's headroom
    (admission checks a static headroom; this prevents one round from
    collectively overshooting it).  Non-preemptible pods additionally
    prefix-check min headroom at their own quota.
    """
    qid = jnp.maximum(pods.quota_id, 0)
    has_quota = pods.quota_id >= 0
    checked = quota.checked[qid]                       # (P, R)
    req_m = jnp.where(checked, requests, 0)
    ok = jnp.ones(pods.capacity, bool)
    depth = quota.chain.shape[1]
    for d in range(depth):
        anc = quota.chain[qid, d]                      # (P,)
        act_d = active & has_quota & (anc >= 0)
        acc = _prefix_accept(
            jnp.maximum(anc, 0), req_m, quota.headroom, order, act_d
        )
        ok = ok & (acc | ~act_d)
    np_act = active & has_quota & pods.non_preemptible
    np_acc = _prefix_accept(qid, req_m, quota.min_headroom, order, np_act)
    ok = ok & (np_acc | ~np_act)
    return ok | ~has_quota


@struct.dataclass
class _RoundCarry:
    requested: jax.Array      # (N, R)
    assignments: jax.Array    # (P,)
    active: jax.Array         # (P,)
    quota: QuotaDeviceState | None


#: candidate-selection strategies for ``select_candidates``:
#: - "exact":  XLA score + exact ``lax.top_k`` on the int ranking key
#: - "approx": XLA score + ``lax.approx_max_k`` on a 24-bit float key
#:             (~0.95 recall on TPU; the CPU lowering is exact, but the
#:             float-key quantization is exercised on every backend)
#: - "chunked": the approx reduction over pod CHUNKS via ``lax.map`` —
#:             bit-identical rows to "approx" (global row offsets feed the
#:             rotation), but peak memory is (chunk, N), not (P, N): at
#:             the 50k x 10,240 shape the unchunked path materializes
#:             ~2 GB per (P, N) tensor (scores, feasible, ranking keys),
#:             the chunked path ~160 MB per (4096, N) block
#: - "chunked_exact": the chunked schedule with ``lax.top_k`` on the
#:             exact int keys instead of ``approx_max_k`` on the float
#:             keys — bit-identical rows to "exact" at chunked peak
#:             memory.  The TPU fallback when the measured approx_max_k
#:             recall strands pods (bench_recall.py's decision rule):
#:             the only other recall-exact option materializes (P, N)
#: - "auto":   "approx" on TPU, "exact" elsewhere
#:
#: (a Pallas streaming kernel ("fused") lived here through round 5 —
#: deleted per the round-4 verdict after four rounds with no TPU time to
#: compile it; the chunked paths already avoid the (P, N) HBM
#: materialization with zero compile risk.  git history has the kernel.)
CANDIDATE_METHODS = ("auto", "exact", "approx", "chunked",
                     "chunked_exact")


def batch_assign(
    state: ClusterState,
    pods: PodBatch,
    cfg: ScoringConfig,
    quota: QuotaDeviceState | None = None,
    k: int = 32,
    rounds: int = 12,
    spread_bits=(5, 15),
    method: str = "auto",
):
    """Assign a pending batch in data-parallel propose/accept rounds.

    Same signature/returns as ``greedy_assign``: (assignments, new_state,
    new_quota).  assignments is (P,) int32, -1 = unassigned.

    ``spread_bits`` controls the candidate-diversity/score trade-off (see
    ``select_candidates``): an int ranks all k candidates by one quantized
    key; the default STRATIFIED ``(5, 15)`` splits k between a
    score-faithful stratum (buckets of 32 — measured at or above exact
    greedy's mean chosen score at 2k nodes x 10k pods) and a pure-rotation
    coverage stratum, because a single sb=5 key strands 14% of a fully
    schedulable 50k-pod queue at 10,240 nodes once the top score band
    fills (see PERF_NOTES.md round-3 sweeps: sb=5 86.4% assigned,
    stratified and deep-spread variants 100%).

    ``method`` picks the candidate-selection strategy (CANDIDATE_METHODS);
    every method is force-selectable on every backend so CI can cover the
    TPU-serving branches on CPU.  Candidate recall is approximate for
    "approx"/"chunked"; acceptance always enforces fit and quota exactly.
    """
    cand_key, cand_node = select_candidates(
        state, pods, cfg, k=k,
        spread_bits=spread_bits, method=method)
    return _assign_rounds(state, pods, quota, cand_key, cand_node, rounds)


def select_candidates(
    state: ClusterState,
    pods: PodBatch,
    cfg: ScoringConfig,
    k: int = 32,
    spread_bits=(5, 15),
    method: str = "auto",
):
    """(cand_key, cand_node), each (P, k): the candidate-selection stage of
    ``batch_assign``, exposed separately so profiling can time it apart
    from the propose/accept rounds.  See CANDIDATE_METHODS.

    ``spread_bits`` may be an int (one quantization depth) or a tuple of
    depths — STRATIFIED selection: k splits evenly across the strata, each
    stratum picks its share by its own quantized ranking key, and the
    first stratum's key orders all candidates inside the rounds.  The
    default ``(5, 15)`` pairs a score-faithful stratum (buckets of 32 —
    best placement quality; measured above exact greedy's mean chosen
    score at 2k nodes) with a pure-rotation coverage stratum (score-free
    consecutive-window candidates) — at the 50k x 10,240 north-star shape
    a single sb=5 key strands 14% of a fully-schedulable queue when the
    top score band fills, while the coverage stratum guarantees every pod
    k/2 uniformly-spread fallbacks (measured: 100% assigned).  Duplicate
    nodes between strata just idle a slot.  Scoring runs ONCE regardless
    of strata count; only the cheap top-k reduction repeats."""
    if method not in CANDIDATE_METHODS:
        raise ValueError(f"unknown candidate method {method!r}; "
                         f"one of {CANDIDATE_METHODS}")
    if method == "auto":
        method = "approx" if jax.default_backend() == "tpu" else "exact"
    strata = (spread_bits if isinstance(spread_bits, (tuple, list))
              else (spread_bits,))
    if method in ("chunked", "chunked_exact"):
        return _chunked_candidates(state, pods, cfg, k=k, strata=strata,
                                   method=method)
    scores, feasible = score_pods(state, pods, cfg)
    return _reduce_candidates(scores, feasible, strata,
                              min(k, scores.shape[1]), method)


def _reduce_candidates(scores, feasible, strata, k: int, method: str,
                       row_offset=0):
    """The (scores, feasible) -> (cand_key, cand_node) reduction shared by
    the whole-batch and chunked paths."""
    order_key = _ranked_scores(scores, feasible, strata[0], row_offset)
    splits = _stratum_splits(k, len(strata))
    nodes = []
    for sb, k_i in zip(strata, splits):
        if k_i == 0:
            continue
        key = (order_key if sb == strata[0]
               else _ranked_scores(scores, feasible, sb, row_offset))
        if method in ("approx", "chunked") and k_i < key.shape[1]:
            # TPU-optimized partial reduction. approx_max_k needs a float
            # key exact within float32's 24-bit mantissa, so candidates
            # are chosen by the quantized score plus as many HIGH bits of
            # the rotated tie-break as fit (high bits keep the
            # closest-after-rotation ordering that fans pods out; low
            # bits would scramble it); the exact int keys are then
            # gathered for in-round ordering.  Candidate RECALL is
            # approximate (~recall_target on TPU; the CPU lowering of
            # approx_max_k is exact, so CPU recall loss comes only from
            # the float-key quantization).  Acceptance still enforces fit
            # and quota exactly.
            score_bits = (30 - _TB_BITS) - sb   # quantized field width
            shift = min(_TB_BITS, max(24 - score_bits, 0))
            fkey = jnp.where(
                key >= 0,
                ((key >> _TB_BITS) << shift
                 | (key & ((1 << _TB_BITS) - 1)) >> (_TB_BITS - shift)
                 ).astype(jnp.float32),
                -1.0)
            _, idx = jax.lax.approx_max_k(
                fkey, k_i, recall_target=0.95, aggregate_to_topk=True)
            nodes.append(idx.astype(jnp.int32))
        else:
            _, idx = jax.lax.top_k(key, k_i)
            nodes.append(idx)
    cand_node = jnp.concatenate(nodes, axis=1) if len(nodes) > 1 else nodes[0]
    # the first stratum's key orders every candidate in the rounds, so a
    # coverage-stratum node competes on the same score scale (gathering
    # also yields -1 for infeasible slots of short candidate lists)
    cand_key = jnp.take_along_axis(order_key, cand_node, axis=1)
    return cand_key, cand_node


#: pod-chunk width for method="chunked": peak score memory is
#: (CANDIDATE_CHUNK, N) — 4096 x 10,240 x int32 = 160 MB at the
#: north-star shape, vs ~2 GB per (P, N) tensor unchunked
CANDIDATE_CHUNK = 4096


def _chunked_candidates(state, pods, cfg, k: int, strata,
                        chunk: int = CANDIDATE_CHUNK,
                        method: str = "chunked"):
    """The chunked reduction over pods: ``lax.map`` scores one
    (chunk, N) block at a time and reduces it to (chunk, k) before the
    next block's scores exist, so no (P, N) tensor is ever materialized.
    Rows are bit-identical to ``method="approx"`` (or, for
    ``method="chunked_exact"``, to ``method="exact"``) — scoring,
    ranking (global row offsets) and the per-row reduction are all
    row-independent; chunking only changes the execution schedule."""
    p = pods.capacity
    k = min(k, state.capacity)
    chunk = min(chunk, p)   # a small batch must not score 4096-row pads
    n_chunks = -(-p // chunk)
    padded = n_chunks * chunk

    def pad_rows(a):
        # every PodBatch field is per-pod along axis 0 (the compact()
        # invariant), so the whole pytree pads uniformly; zero/False
        # padding means invalid rows, which reduce to key -1
        pad_width = [(0, padded - p)] + [(0, 0)] * (a.ndim - 1)
        return jnp.pad(a, pad_width)

    stacked = jax.tree.map(pad_rows, pods)

    def reshape_rows(a):
        return (None if a is None
                else a.reshape((n_chunks, chunk) + a.shape[1:]))

    offsets = jnp.arange(n_chunks, dtype=jnp.int32) * chunk

    def body(args):
        offset, sub = args
        scores, feasible = score_pods(state, sub, cfg)
        return _reduce_candidates(scores, feasible, strata, k,
                                  method, row_offset=offset)

    sub_batches = jax.tree.map(reshape_rows, stacked)
    keys, nodes = jax.lax.map(body, (offsets, sub_batches))
    return (keys.reshape(padded, -1)[:p],
            nodes.reshape(padded, -1)[:p])


def _stratum_splits(k: int, n: int) -> list[int]:
    """Split k as evenly as possible over n strata (first strata get the
    remainder)."""
    base, rem = divmod(k, n)
    return [base + (1 if i < rem else 0) for i in range(n)]


def _assign_rounds(state, pods, quota, cand_key, cand_node, rounds):
    """The shared propose/accept stage over (P, k) candidates."""
    cand_valid = cand_key >= 0

    order = jnp.lexsort((jnp.arange(pods.capacity), -pods.priority))
    active0 = pods.valid & jnp.any(cand_valid, axis=1)

    carry = _RoundCarry(
        requested=state.node_requested,
        assignments=jnp.full(pods.capacity, -1, jnp.int32),
        active=active0,
        quota=quota,
    )

    def round_body(_, c: _RoundCarry) -> _RoundCarry:
        free = jnp.where(
            state.node_valid[:, None], state.node_allocatable - c.requested, 0
        )
        # each pod's best candidate whose node still fits its request
        cand_free = free[cand_node]                    # (P, k, R)
        fits = jnp.all(
            (pods.requests[:, None, :] <= cand_free)
            | (pods.requests[:, None, :] == 0),
            axis=-1,
        ) & cand_valid
        best = jnp.argmax(jnp.where(fits, cand_key, -1), axis=1)
        has = jnp.take_along_axis(fits, best[:, None], axis=1)[:, 0]
        choice = jnp.take_along_axis(cand_node, best[:, None], axis=1)[:, 0]

        act = c.active & has
        if c.quota is not None:
            act = act & quota_admission_mask(
                c.quota, pods.requests, pods.quota_id, pods.non_preemptible
            )
        accept = _prefix_accept(choice, pods.requests, free, order, act)
        if c.quota is not None:
            accept = accept & _quota_prefix_accept(
                c.quota, pods.requests, pods, order, act
            )

        safe = jnp.where(accept, choice, 0)
        add = jnp.where(accept[:, None], pods.requests, 0)
        requested = c.requested.at[safe].add(add)
        new_quota = c.quota
        if new_quota is not None:
            new_quota = charge_quota_batch(
                new_quota, pods.requests, pods.quota_id, accept,
                pods.non_preemptible,
            )
        return _RoundCarry(
            requested=requested,
            assignments=jnp.where(accept, choice, c.assignments),
            # free capacity and quota headroom only shrink within a solve,
            # so a pod with no fitting admitted candidate now (act=False)
            # can never gain one: drop it from active so the early-exit
            # condition actually converges
            active=act & ~accept,
            quota=new_quota,
        )

    # early-exit loop: most rounds converge long before the bound (pods
    # either accept or run out of fitting candidates); the tail rounds are
    # pure waste at the north-star shape
    def cond(loop_carry):
        i, c = loop_carry
        return (i < rounds) & jnp.any(c.active)

    def body(loop_carry):
        i, c = loop_carry
        return i + 1, round_body(i, c)

    _, carry = jax.lax.while_loop(cond, body, (jnp.int32(0), carry))
    new_state = state.replace(node_requested=carry.requested)
    return carry.assignments, new_state, carry.quota
