"""Batch-parallel assignment: propose/accept rounds instead of an O(P) scan.

``greedy_assign`` (ops/assignment.py) is the exact sequential solver — one
``lax.scan`` step per pod, 50k dependent steps at the north-star shape.  This
module is the throughput path: the whole pending queue lands in a handful of
data-parallel rounds.

    1. ONE fused Filter+Score pass over the (P, N) problem (same kernels as
       ``score_pods``), with a per-pod rotated tie-break so identical pods
       spread over equal-scored nodes instead of stampeding one argmax;
    2. ``lax.top_k`` -> each pod's k best candidate nodes, (P, k);
    3. K propose/accept rounds on the small (P, k) tensors: every active pod
       proposes its best candidate that still fits, conflicts are resolved by
       a segmented prefix-sum over requests in priority order (higher-priority
       pods win a contended node, exactly one device-wide sort per round), and
       elastic-quota headroom is enforced by the same prefix trick per
       ancestor level of the quota chain.

Semantics vs the reference / greedy_assign:
- priority order in conflicts matches the scheduler queue order
  (priority desc, stable) — the prefix acceptance is the tensor analog of
  higher-priority pods going through scheduleOne first;
- capacity and quota feedback happen per round (snapshot granularity) rather
  than per pod: scores are not recomputed between two pods of the same round,
  like the upstream parallel Filter/Score over one snapshot;
- a pod only ever considers its top-k candidates; under extreme contention a
  pod can go unassigned in this solve even though some node below its top-k
  would fit (it retries next scheduler round).  k and the round count bound
  the approximation.

Reference parity anchors: scoring pipeline per cmd/koord-scheduler/main.go
plugin registry; quota admission per elasticquota/plugin.go:256-304; the
conflict rule mirrors upstream queue ordering (priority, then FIFO).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from koordinator_tpu.ops.assignment import ScoringConfig, score_pods
from koordinator_tpu.quota.admission import (
    QuotaDeviceState,
    charge_quota_batch,
    quota_admission_mask,
)
from koordinator_tpu.state.cluster_state import ClusterState, PodBatch

#: tie-break field width of the PACKED ranking key: node index occupies the
#: low bits, the quantized score the high bits, of one int32
_TB_BITS = 15
_SCORE_CLIP = (1 << 30 - _TB_BITS) - 1

#: node capacities up to this fit the packed single-int32 key regime
#: (score and rotated tie-break in one word, one ``lax.top_k``).  Larger
#: capacities switch to the WIDE regime: the ranking key carries the
#: quantized score alone and the rotated tie-break rides a second int32,
#: compared lexicographically (a two-operand ``lax.sort`` at selection,
#: a two-stage argmax in the rounds).  The packed regime is bit-identical
#: to the historical behavior; the wide regime never aliases because
#: nothing is packed.
PACKED_NODE_CAPACITY = 1 << _TB_BITS

#: hard node-capacity ceiling of the solver: node rows index as
#: nonnegative int32 and the tie-break rotation arithmetic
#: (``rot_id * 7919`` against a node id) must stay inside int32.  The
#: old 2**15 packing wall is gone — past it the wide two-key regime
#: ranks exactly — so this guard is about integer width, not packing.
MAX_NODE_CAPACITY = 1 << 30


def check_node_capacity(n: int) -> None:
    """Raise if a node capacity exceeds the ranking key's ceiling."""
    if n > MAX_NODE_CAPACITY:
        raise ValueError(
            f"node capacity {n} exceeds the batched solver's ranking-key "
            f"ceiling of {MAX_NODE_CAPACITY} (= 2**30): node rows must "
            "index as nonnegative int32 and the rotated tie-break "
            "arithmetic must not overflow.  Node-axis mesh sharding "
            "(parallel/sharded.py) spreads the per-device footprint but "
            "keys stay global-int32; a cluster past 2**30 nodes needs a "
            "64-bit key carrier.")


def _packed_regime(n_total: int) -> bool:
    """True when ``n_total`` node rows fit the packed int32 key."""
    return n_total <= PACKED_NODE_CAPACITY


def _ranked_scores(
    scores: jnp.ndarray, feasible: jnp.ndarray, spread_bits: int = 0,
    rot_id: jnp.ndarray | None = None,
    node_ids: jnp.ndarray | None = None,
    n_total: int | None = None,
) -> jnp.ndarray:
    """(P, N) int32 ranking key: score in the high bits, a per-pod rotated
    node index in the low bits.  Equal-scored nodes order differently for
    every pod, so homogeneous pods fan out instead of all picking node 0
    (selectHost randomizes among maxima upstream; rotation is the
    deterministic equivalent).

    ``spread_bits`` quantizes the score into buckets of ``2**spread_bits``
    before ranking.  With exact scores, every pod ranks nodes near-identically
    and the whole queue's top-k candidate sets collapse onto the same few
    nodes — at 50k pods x 10k nodes that strands >90% of a schedulable queue.
    Bucketing widens the tie groups so the rotation fans candidates over ALL
    near-best nodes; the score sacrifice is bounded by the bucket width
    (upstream's selectHost already treats equal-enough scores as
    interchangeable: defaultPodTopologySpread jitter, selectHost randomness).

    ``rot_id`` is the per-pod rotation identity (``PodBatch.rot_id``;
    defaults to the batch row index).  Keys are a pure function of
    (rot_id, node id, score) — independent of the pod's batch ROW — which
    is what lets chunked reductions and the incremental candidate cache
    reproduce any single row bit-for-bit.  ``node_ids``/``n_total`` score
    a gathered COLUMN SUBSET (the dirty-node refresh): the tie-break uses
    the nodes' GLOBAL ids modulo the full capacity, so a subset column's
    key equals the same node's key in a full (P, N) pass.
    """
    return _rank_parts(scores, feasible, spread_bits, rot_id,
                       node_ids, n_total)[0]


# koordlint: shape[ret0: PxN i32 -1..1073741823, ret1: PxN i32 0..1073741823]
def _rank_parts(
    scores: jnp.ndarray, feasible: jnp.ndarray, spread_bits: int = 0,
    rot_id: jnp.ndarray | None = None,
    node_ids: jnp.ndarray | None = None,
    n_total: int | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(key, tb) pair behind :func:`_ranked_scores`.

    Packed regime (``n_total <= PACKED_NODE_CAPACITY``): ``key`` is the
    historical single int32 ``(q << _TB_BITS) | tb`` and already encodes
    the tie-break.  Wide regime: ``key`` is the quantized score alone and
    callers break ties lexicographically with ``tb`` (``_topk_by_rank``,
    the rounds' two-stage argmax).  ``tb`` is returned in both regimes so
    shard-local selections can always merge on the same (key, tb) scale.
    """
    p, n = scores.shape
    n_total = n if n_total is None else n_total
    check_node_capacity(n_total)
    if rot_id is None:
        rot_id = jnp.arange(p, dtype=jnp.int32)
    rot = (rot_id.astype(jnp.int32) * 7919)[:, None]
    ids = (jnp.arange(n, dtype=jnp.int32)[None, :] if node_ids is None
           else node_ids.astype(jnp.int32)[None, :])
    tb = (ids - rot) % n_total
    # invert so the SMALLEST rotated distance ranks highest among ties
    tb = (n_total - 1) - tb
    q = jnp.clip(scores, 0, _SCORE_CLIP) >> spread_bits
    key = ((q << _TB_BITS) | tb) if _packed_regime(n_total) else q
    return jnp.where(feasible, key, -1), tb


def _candidate_tb(node: jnp.ndarray, rot_id: jnp.ndarray,
                  n_total: int) -> jnp.ndarray:
    """The (P, k) rotated tie-break of cached candidate node rows — the
    same pure function of (rot_id, node) that :func:`_rank_parts` packs
    (packed regime) or returns alongside (wide regime)."""
    rot = (rot_id.astype(jnp.int32) * 7919)[:, None]
    return (n_total - 1) - ((node - rot) % n_total)


# koordlint: shape[score: Pxk i32 -1..32767]
def _candidate_keys(score: jnp.ndarray, node: jnp.ndarray,
                    rot_id: jnp.ndarray, spread_bits: int,
                    n_total: int) -> jnp.ndarray:
    """Ranking key recomputed from a CACHED candidate's raw clipped score
    and node row — bit-identical to the :func:`_ranked_scores` key of the
    same (pod, node) pair, so merged and freshly-selected candidates rank
    on one scale.  ``score < 0`` marks an invalid slot."""
    q = score >> spread_bits
    if _packed_regime(n_total):
        key = (q << _TB_BITS) | _candidate_tb(node, rot_id, n_total)
    else:
        key = q
    return jnp.where(score >= 0, key, -1)


def _topk_by_rank(key: jnp.ndarray, tb: jnp.ndarray, k: int,
                  n_total: int) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Exact per-row top-k columns by (key, tb) rank, descending —
    ``lax.top_k`` when the packed key already encodes the tie-break, a
    two-operand lexicographic ``lax.sort`` in the wide regime.  Returns
    (key_sel, col_idx) like ``lax.top_k``.  Rank pairs of feasible
    columns are unique per row (tb is a permutation of node ids), so the
    result is order-deterministic in both regimes."""
    if _packed_regime(n_total):
        return jax.lax.top_k(key, k)
    n = key.shape[-1]
    cols = jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32), key.shape)
    key_s, _, idx_s = jax.lax.sort((key, tb, cols), num_keys=2)
    sl = slice(n - k, None)
    return (jnp.flip(key_s[..., sl], -1).astype(key.dtype),
            jnp.flip(idx_s[..., sl], -1))


def _prefix_accept(
    choice: jnp.ndarray,     # (P,) int32 proposed segment (node/quota row)
    requests: jnp.ndarray,   # (P, R) int32
    free: jnp.ndarray,       # (S, R) int32 segment headroom
    order: jnp.ndarray,      # (P,) priority-descending pod order
    active: jnp.ndarray,     # (P,) bool — proposers this round
) -> jnp.ndarray:
    """(P,) bool: cumulative request per segment (taken in ``order`` among
    active proposers) fits the segment's headroom, counting the pod itself.

    This is the round's conflict resolution: the tensor equivalent of
    higher-priority pods passing through the scheduling cycle first.

    Fast path: when NO segment is oversubscribed (every segment's total
    proposed request fits its headroom — the common case from round 2 on,
    once the first round's land grab settles), every active proposer's
    prefix trivially fits, so the answer is ``active`` and the device-wide
    stable sort is skipped via ``lax.cond``.  One cheap segment-sum pays
    for the detection; the sorted path below remains the general case and
    the single source of truth for contended rounds.
    """
    s = free.shape[0]
    choice_free = jnp.where(
        active[:, None], free[jnp.clip(choice, 0, s - 1)], 0)
    return _prefix_accept_choice(choice, requests, choice_free, s,
                                 order, active)


def _prefix_accept_choice(
    choice: jnp.ndarray,       # (P,) int32 proposed segment
    requests: jnp.ndarray,     # (P, R)
    choice_free: jnp.ndarray,  # (P, R) headroom of each pod's OWN segment
    num_segments: int,
    order: jnp.ndarray,
    active: jnp.ndarray,
) -> jnp.ndarray:
    """The choice-indexed core of :func:`_prefix_accept`: the segment
    headroom arrives pre-gathered per pod instead of as an (S, R) table.
    This is the form the node-sharded rounds reuse — each shard psums
    the headroom of the candidates it owns into ``choice_free``, then
    every shard runs this replicated decision identically (see
    parallel/sharded.py for the exactness argument)."""
    s = num_segments
    seg = jnp.where(active, choice, s)            # inactive -> overflow row
    req_act = jnp.where(active[:, None], requests, 0)
    totals = jax.ops.segment_sum(req_act, seg, num_segments=s + 1)
    # a segment is oversubscribed iff one of its own proposers sees its
    # total exceed the (shared) headroom — same predicate as scanning
    # the (S, R) table, evaluated through the pods that propose there
    contended = jnp.any(active[:, None] & (totals[seg] > choice_free))

    def fast(_):
        # total per segment fits => every within-segment prefix fits
        return active

    def slow(_):
        return _prefix_accept_sorted_choice(seg, requests, choice_free,
                                            order, active)

    return jax.lax.cond(contended, slow, fast, None)


def _prefix_accept_sorted(seg, requests, free, order, active):
    """The general contended-round path over an (S, R) headroom table:
    kept as the spec/test surface; delegates to the choice-indexed core."""
    r = requests.shape[1]
    free_pad = jnp.concatenate([free, jnp.zeros((1, r), free.dtype)])
    return _prefix_accept_sorted_choice(seg, requests, free_pad[seg],
                                        order, active)


def _prefix_accept_sorted_choice(seg, requests, choice_free, order, active):
    """Contended-round acceptance: stable sort groups segments in
    priority order, a segmented prefix-sum checks cumulative fit against
    each pod's own-segment headroom."""
    p, r = requests.shape
    seg_o = seg[order]
    req_o = jnp.where(active[order][:, None], requests[order], 0)
    free_o = choice_free[order]
    pos = jnp.argsort(seg_o, stable=True)         # group segments, keep order
    seg_s = seg_o[pos]
    req_s = req_o[pos]
    cum = jnp.cumsum(req_s, axis=0)
    excl = cum - req_s
    is_start = jnp.concatenate(
        [jnp.ones((1,), bool), seg_s[1:] != seg_s[:-1]]
    )
    # propagate each segment's starting cumulative value (cum is
    # non-decreasing, so a running max of start markers yields the most
    # recent segment start)
    base = jax.lax.associative_scan(
        jnp.maximum, jnp.where(is_start[:, None], excl, -1), axis=0
    )
    prefix = cum - base                           # within-segment incl. self
    fits = jnp.all((prefix <= free_o[pos]) | (req_s == 0), axis=-1)
    out = jnp.zeros(p, bool).at[order[pos]].set(fits)
    return out & active


def _quota_prefix_accept(
    quota: QuotaDeviceState,
    requests: jnp.ndarray,
    pods: PodBatch,
    order: jnp.ndarray,
    active: jnp.ndarray,
) -> jnp.ndarray:
    """(P,) bool: within-round quota headroom conflict resolution.

    For every ancestor level of the quota chain, the cumulative masked
    request of this round's proposers must fit the ancestor's headroom
    (admission checks a static headroom; this prevents one round from
    collectively overshooting it).  Non-preemptible pods additionally
    prefix-check min headroom at their own quota.
    """
    qid = jnp.maximum(pods.quota_id, 0)
    has_quota = pods.quota_id >= 0
    checked = quota.checked[qid]                       # (P, R)
    req_m = jnp.where(checked, requests, 0)
    ok = jnp.ones(pods.capacity, bool)
    depth = quota.chain.shape[1]
    for d in range(depth):
        anc = quota.chain[qid, d]                      # (P,)
        act_d = active & has_quota & (anc >= 0)
        acc = _prefix_accept(
            jnp.maximum(anc, 0), req_m, quota.headroom, order, act_d
        )
        ok = ok & (acc | ~act_d)
    np_act = active & has_quota & pods.non_preemptible
    np_acc = _prefix_accept(qid, req_m, quota.min_headroom, order, np_act)
    ok = ok & (np_acc | ~np_act)
    return ok | ~has_quota


@struct.dataclass
class _RoundCarry:
    requested: jax.Array      # (N, R)
    assignments: jax.Array    # (P,)
    active: jax.Array         # (P,)
    quota: QuotaDeviceState | None


#: candidate-selection strategies for ``select_candidates``:
#: - "exact":  XLA score + exact ``lax.top_k`` on the int ranking key
#: - "approx": XLA score + ``lax.approx_max_k`` on a 24-bit float key
#:             (~0.95 recall on TPU; the CPU lowering is exact, but the
#:             float-key quantization is exercised on every backend)
#: - "chunked": the approx reduction over pod CHUNKS via ``lax.map`` —
#:             bit-identical rows to "approx" (global row offsets feed the
#:             rotation), but peak memory is (chunk, N), not (P, N): at
#:             the 50k x 10,240 shape the unchunked path materializes
#:             ~2 GB per (P, N) tensor (scores, feasible, ranking keys),
#:             the chunked path ~160 MB per (4096, N) block
#: - "chunked_exact": the chunked schedule with ``lax.top_k`` on the
#:             exact int keys instead of ``approx_max_k`` on the float
#:             keys — bit-identical rows to "exact" at chunked peak
#:             memory.  The TPU fallback when the measured approx_max_k
#:             recall strands pods (bench_recall.py's decision rule):
#:             the only other recall-exact option materializes (P, N)
#: - "auto":   "approx" on TPU, "exact" elsewhere
#:
#: (a Pallas streaming kernel ("fused") lived here through round 5 —
#: deleted per the round-4 verdict after four rounds with no TPU time to
#: compile it; the chunked paths already avoid the (P, N) HBM
#: materialization with zero compile risk.  git history has the kernel.)
CANDIDATE_METHODS = ("auto", "exact", "approx", "chunked",
                     "chunked_exact")


def batch_assign(
    state: ClusterState,
    pods: PodBatch,
    cfg: ScoringConfig,
    quota: QuotaDeviceState | None = None,
    k: int = 32,
    rounds: int = 12,
    spread_bits=(5, 15),
    method: str = "auto",
):
    """Assign a pending batch in data-parallel propose/accept rounds.

    Same signature/returns as ``greedy_assign``: (assignments, new_state,
    new_quota).  assignments is (P,) int32, -1 = unassigned.

    ``spread_bits`` controls the candidate-diversity/score trade-off (see
    ``select_candidates``): an int ranks all k candidates by one quantized
    key; the default STRATIFIED ``(5, 15)`` splits k between a
    score-faithful stratum (buckets of 32 — measured at or above exact
    greedy's mean chosen score at 2k nodes x 10k pods) and a pure-rotation
    coverage stratum, because a single sb=5 key strands 14% of a fully
    schedulable 50k-pod queue at 10,240 nodes once the top score band
    fills (see PERF_NOTES.md round-3 sweeps: sb=5 86.4% assigned,
    stratified and deep-spread variants 100%).

    ``method`` picks the candidate-selection strategy (CANDIDATE_METHODS);
    every method is force-selectable on every backend so CI can cover the
    TPU-serving branches on CPU.  Candidate recall is approximate for
    "approx"/"chunked"; acceptance always enforces fit and quota exactly.
    """
    cand_key, cand_node = select_candidates(
        state, pods, cfg, k=k,
        spread_bits=spread_bits, method=method)
    return _assign_rounds(state, pods, quota, cand_key, cand_node, rounds)


def select_candidates(
    state: ClusterState,
    pods: PodBatch,
    cfg: ScoringConfig,
    k: int = 32,
    spread_bits=(5, 15),
    method: str = "auto",
    with_scores: bool = False,
):
    """(cand_key, cand_node), each (P, k): the candidate-selection stage of
    ``batch_assign``, exposed separately so profiling can time it apart
    from the propose/accept rounds.  See CANDIDATE_METHODS.

    ``spread_bits`` may be an int (one quantization depth) or a tuple of
    depths — STRATIFIED selection: k splits evenly across the strata, each
    stratum picks its share by its own quantized ranking key, and the
    first stratum's key orders all candidates inside the rounds.  The
    default ``(5, 15)`` pairs a score-faithful stratum (buckets of 32 —
    best placement quality; measured above exact greedy's mean chosen
    score at 2k nodes) with a pure-rotation coverage stratum (score-free
    consecutive-window candidates) — at the 50k x 10,240 north-star shape
    a single sb=5 key strands 14% of a fully-schedulable queue when the
    top score band fills, while the coverage stratum guarantees every pod
    k/2 uniformly-spread fallbacks (measured: 100% assigned).  Duplicate
    nodes between strata just idle a slot.  Scoring runs ONCE regardless
    of strata count; only the cheap top-k reduction repeats.

    ``with_scores=True`` additionally returns the selected slots' raw
    clipped composite scores, (P, k) int32 with -1 for invalid slots —
    the persistent form the incremental candidate cache needs to
    recompute any stratum's ranking key without a full rescore."""
    if method not in CANDIDATE_METHODS:
        raise ValueError(f"unknown candidate method {method!r}; "
                         f"one of {CANDIDATE_METHODS}")
    if method == "auto":
        method = "approx" if jax.default_backend() == "tpu" else "exact"
    strata = (spread_bits if isinstance(spread_bits, (tuple, list))
              else (spread_bits,))
    if method in ("chunked", "chunked_exact"):
        return _chunked_candidates(state, pods, cfg, k=k, strata=strata,
                                   method=method, with_scores=with_scores)
    scores, feasible = score_pods(state, pods, cfg)
    return _reduce_candidates(scores, feasible, strata,
                              min(k, scores.shape[1]), method,
                              pods.rot_id, with_scores=with_scores)


def _reduce_candidates(scores, feasible, strata, k: int, method: str,
                       rot_id=None, with_scores: bool = False,
                       node_ids=None, n_total: int | None = None):
    """The (scores, feasible) -> (cand_key, cand_node) reduction shared by
    the whole-batch, chunked and shard-local paths.  ``node_ids``/
    ``n_total`` score a gathered COLUMN SUBSET (a shard's local columns):
    keys use global node ids and ``cand_node`` returns global rows."""
    n_total = scores.shape[1] if n_total is None else n_total
    order_key, order_tb = _rank_parts(scores, feasible, strata[0], rot_id,
                                      node_ids, n_total)
    splits = _stratum_splits(k, len(strata))
    nodes = []
    for sb, k_i in zip(strata, splits):
        if k_i == 0:
            continue
        key, tb = ((order_key, order_tb) if sb == strata[0]
                   else _rank_parts(scores, feasible, sb, rot_id,
                                    node_ids, n_total))
        if method in ("approx", "chunked") and k_i < key.shape[1]:
            # TPU-optimized partial reduction. approx_max_k needs a float
            # key exact within float32's 24-bit mantissa, so candidates
            # are chosen by the quantized score plus as many HIGH bits of
            # the rotated tie-break as fit (high bits keep the
            # closest-after-rotation ordering that fans pods out; low
            # bits would scramble it); the exact int keys are then
            # gathered for in-round ordering.  Candidate RECALL is
            # approximate (~recall_target on TPU; the CPU lowering of
            # approx_max_k is exact, so CPU recall loss comes only from
            # the float-key quantization).  Acceptance still enforces fit
            # and quota exactly.
            score_bits = (30 - _TB_BITS) - sb   # quantized field width
            if _packed_regime(n_total):
                shift = min(_TB_BITS, max(24 - score_bits, 0))
                fkey = jnp.where(
                    key >= 0,
                    ((key >> _TB_BITS) << shift
                     | (key & ((1 << _TB_BITS) - 1)) >> (_TB_BITS - shift)
                     ).astype(jnp.float32),
                    -1.0)
            else:
                # wide regime: q rides the float key's high integer bits,
                # the top tie-break bits fill the rest of the 24-bit
                # mantissa (q < 2**score_bits keeps the sum exact)
                tb_bits = max((n_total - 1).bit_length(), 1)
                shift = max(24 - score_bits, 0)
                fkey = jnp.where(
                    key >= 0,
                    # koordlint: ignore[dtype-regime] -- trace-time Python int shift (arbitrary precision) feeding a float32 scale, never int32 array math
                    key.astype(jnp.float32) * float(1 << shift)
                    + (tb >> max(tb_bits - shift, 0)).astype(jnp.float32),
                    -1.0)
            _, idx = jax.lax.approx_max_k(
                fkey, k_i, recall_target=0.95, aggregate_to_topk=True)
            nodes.append(idx.astype(jnp.int32))
        else:
            _, idx = _topk_by_rank(key, tb, k_i, n_total)
            nodes.append(idx)
    cand_cols = jnp.concatenate(nodes, axis=1) if len(nodes) > 1 else nodes[0]
    # the first stratum's key orders every candidate in the rounds, so a
    # coverage-stratum node competes on the same score scale (gathering
    # also yields -1 for infeasible slots of short candidate lists)
    cand_key = jnp.take_along_axis(order_key, cand_cols, axis=1)
    cand_node = (cand_cols if node_ids is None
                 else node_ids.astype(jnp.int32)[cand_cols])
    if with_scores:
        raw = jnp.take_along_axis(
            jnp.clip(scores, 0, _SCORE_CLIP), cand_cols, axis=1)
        return cand_key, cand_node, jnp.where(cand_key >= 0, raw, -1)
    return cand_key, cand_node


#: pod-chunk width for method="chunked": peak score memory is
#: (CANDIDATE_CHUNK, N) — 4096 x 10,240 x int32 = 160 MB at the
#: north-star shape, vs ~2 GB per (P, N) tensor unchunked
CANDIDATE_CHUNK = 4096


def _chunked_candidates(state, pods, cfg, k: int, strata,
                        chunk: int = CANDIDATE_CHUNK,
                        method: str = "chunked",
                        with_scores: bool = False):
    """The chunked reduction over pods: ``lax.map`` scores one
    (chunk, N) block at a time and reduces it to (chunk, k) before the
    next block's scores exist, so no (P, N) tensor is ever materialized.
    Rows are bit-identical to ``method="approx"`` (or, for
    ``method="chunked_exact"``, to ``method="exact"``) — scoring,
    ranking (per-pod rot_id) and the per-row reduction are all
    row-independent; chunking only changes the execution schedule."""
    p = pods.capacity
    k = min(k, state.capacity)
    chunk = min(chunk, p)   # a small batch must not score 4096-row pads
    n_chunks = -(-p // chunk)
    padded = n_chunks * chunk

    def pad_rows(a):
        # every PodBatch field is per-pod along axis 0 (the compact()
        # invariant), so the whole pytree pads uniformly; zero/False
        # padding means invalid rows, which reduce to key -1
        pad_width = [(0, padded - p)] + [(0, 0)] * (a.ndim - 1)
        return jnp.pad(a, pad_width)

    stacked = jax.tree.map(pad_rows, pods)

    def reshape_rows(a):
        return (None if a is None
                else a.reshape((n_chunks, chunk) + a.shape[1:]))

    def body(sub):
        scores, feasible = score_pods(state, sub, cfg)
        return _reduce_candidates(scores, feasible, strata, k,
                                  method, sub.rot_id,
                                  with_scores=with_scores)

    sub_batches = jax.tree.map(reshape_rows, stacked)
    out = jax.lax.map(body, sub_batches)
    return tuple(a.reshape(padded, -1)[:p] for a in out)


def _stratum_splits(k: int, n: int) -> list[int]:
    """Split k as evenly as possible over n strata (first strata get the
    remainder)."""
    base, rem = divmod(k, n)
    return [base + (1 if i < rem else 0) for i in range(n)]


def _choose_candidate(cand_key, cand_tb, fits):
    """(P,) column of each pod's best FITTING candidate by (key, tb)
    rank.  The packed key encodes the tie-break (``cand_tb`` is None);
    the wide regime runs a two-stage argmax — max key, then max tb among
    the key ties — which equals the lexicographic rank because rank
    pairs of distinct nodes are unique per pod."""
    masked = jnp.where(fits, cand_key, -1)
    if cand_tb is None:
        return jnp.argmax(masked, axis=1)
    best_key = jnp.max(masked, axis=1, keepdims=True)
    return jnp.argmax(
        jnp.where(fits & (masked == best_key), cand_tb, -1), axis=1)


def _assign_rounds(state, pods, quota, cand_key, cand_node, rounds):
    """The shared propose/accept stage over (P, k) candidates."""
    cand_valid = cand_key >= 0
    cand_tb = (None if _packed_regime(state.capacity)
               else _candidate_tb(cand_node, pods.rot_id, state.capacity))

    order = jnp.lexsort((jnp.arange(pods.capacity), -pods.priority))
    active0 = pods.valid & jnp.any(cand_valid, axis=1)

    carry = _RoundCarry(
        requested=state.node_requested,
        assignments=jnp.full(pods.capacity, -1, jnp.int32),
        active=active0,
        quota=quota,
    )

    def round_body(_, c: _RoundCarry) -> _RoundCarry:
        free = jnp.where(
            state.node_valid[:, None], state.node_allocatable - c.requested, 0
        )
        # each pod's best candidate whose node still fits its request
        cand_free = free[cand_node]                    # (P, k, R)
        fits = jnp.all(
            (pods.requests[:, None, :] <= cand_free)
            | (pods.requests[:, None, :] == 0),
            axis=-1,
        ) & cand_valid
        best = _choose_candidate(cand_key, cand_tb, fits)
        has = jnp.take_along_axis(fits, best[:, None], axis=1)[:, 0]
        choice = jnp.take_along_axis(cand_node, best[:, None], axis=1)[:, 0]

        act = c.active & has
        if c.quota is not None:
            act = act & quota_admission_mask(
                c.quota, pods.requests, pods.quota_id, pods.non_preemptible
            )
        accept = _prefix_accept(choice, pods.requests, free, order, act)
        if c.quota is not None:
            accept = accept & _quota_prefix_accept(
                c.quota, pods.requests, pods, order, act
            )

        safe = jnp.where(accept, choice, 0)
        add = jnp.where(accept[:, None], pods.requests, 0)
        requested = c.requested.at[safe].add(add)
        new_quota = c.quota
        if new_quota is not None:
            new_quota = charge_quota_batch(
                new_quota, pods.requests, pods.quota_id, accept,
                pods.non_preemptible,
            )
        return _RoundCarry(
            requested=requested,
            assignments=jnp.where(accept, choice, c.assignments),
            # free capacity and quota headroom only shrink within a solve,
            # so a pod with no fitting admitted candidate now (act=False)
            # can never gain one: drop it from active so the early-exit
            # condition actually converges
            active=act & ~accept,
            quota=new_quota,
        )

    # early-exit loop: most rounds converge long before the bound (pods
    # either accept or run out of fitting candidates); the tail rounds are
    # pure waste at the north-star shape
    def cond(loop_carry):
        i, c = loop_carry
        return (i < rounds) & jnp.any(c.active)

    def body(loop_carry):
        i, c = loop_carry
        return i + 1, round_body(i, c)

    _, carry = jax.lax.while_loop(cond, body, (jnp.int32(0), carry))
    new_state = state.replace(node_requested=carry.requested)
    return carry.assignments, new_state, carry.quota


# ---------------------------------------------------------------------------
# Incremental delta-driven solve: persistent device-resident candidate cache
# ---------------------------------------------------------------------------
#
# Steady-state scheduler rounds arrive as small deltas (a few node upserts,
# a few pod arrivals) yet the full solve pays O(P·N) candidate selection
# every round.  The cache keeps the (P, k) candidate set resident across
# rounds and refreshes it in O(P·D + Pd·N) for D dirty nodes and Pd dirty
# pods:
#
#   1. pods whose cached candidates touch NO dirty node keep them — their
#      cached top-k over clean nodes IS the clean-column top-k (removing
#      entries ranked below the k-th never changes a top-k), so merging in
#      a fresh top-k over the dirty COLUMNS reproduces the full pass's
#      top-k exactly, per stratum;
#   2. pods that are new/changed, or whose cached candidates touch a dirty
#      node (their clean-column top-k is NOT recoverable from the cache),
#      are fully rescored — the scheduler compacts them into a small batch
#      and scatters the fresh rows over the merge's output.
#
# Exactness holds for the exact top_k methods; under "approx"/"chunked"
# the full pass is itself recall-approximate and the refresh (which always
# merges with exact top_k) is just another recall-approximate candidate
# source.  Either way a stale candidate can only cost RECALL, never
# correctness: acceptance (_assign_rounds) re-checks fit and quota exactly
# every round.


@struct.dataclass
class CandidateCache:
    """Device-resident candidate state carried across scheduler rounds."""

    cand_key: jax.Array    # (P, k) int32 stratum-0 ranking key, -1 invalid
    cand_node: jax.Array   # (P, k) int32 node rows
    cand_score: jax.Array  # (P, k) int32 raw clipped score, -1 invalid

    @classmethod
    def build(cls, cand_key, cand_node, cand_score) -> "CandidateCache":
        return cls(cand_key=cand_key, cand_node=cand_node,
                   cand_score=cand_score)


def align_candidate_cache(
    cache: CandidateCache,
    map_rows: jnp.ndarray,   # (P,) int32 cached row per current batch row
    map_ok: jnp.ndarray,     # (P,) bool — current row present in the cache
    dirty_mask: jnp.ndarray,  # (N,) bool — nodes whose state changed
) -> tuple[CandidateCache, jnp.ndarray]:
    """Gather cached rows into the CURRENT batch's row order and flag pods
    whose cached candidates touch a dirty node.  Keys/scores are functions
    of (rot_id, node, score) only — row-independent — so a gathered row is
    exactly the pod's cached candidate set regardless of queue churn.

    Returns (aligned cache, touch): ``touch[i]`` means row i's cached
    candidates intersect the dirty nodes, so the merge alone cannot
    reproduce its full top-k and the pod must rescore fully."""
    node = cache.cand_node[map_rows]
    score = jnp.where(map_ok[:, None], cache.cand_score[map_rows], -1)
    key = jnp.where(map_ok[:, None], cache.cand_key[map_rows], -1)
    touch = jnp.any(dirty_mask[node] & (score >= 0), axis=1)
    return CandidateCache(key, node, score), touch


def refresh_candidates(
    state: ClusterState,
    pods: PodBatch,
    cfg: ScoringConfig,
    cache: CandidateCache,
    dirty_rows: jnp.ndarray,   # (D,) int32, padded; global node rows
    dirty_valid: jnp.ndarray,  # (D,) bool — real (non-pad) entries
    k: int = 32,
    spread_bits=(5, 15),
) -> tuple[jnp.ndarray, CandidateCache]:
    """Segmented per-stratum top-k merge of fresh dirty-COLUMN candidates
    into an (aligned) candidate cache.

    Scores only the (P, D) dirty sub-problem, invalidates cached slots
    that point at dirty nodes, recomputes each stratum's ranking keys from
    the cached raw scores, and keeps the best k_i per stratum of
    (cached ∪ fresh-dirty).  For a pod whose cached candidates touch no
    dirty node this equals the full pass's selection exactly (see module
    section comment); rows the scheduler rescores fully are scattered
    over this function's output afterwards.

    Returns (cand_key, new_cache); cand_node rides the cache.
    """
    strata = (tuple(spread_bits) if isinstance(spread_bits, (tuple, list))
              else (spread_bits,))
    n = state.capacity
    k = min(k, n)
    d = dirty_rows.shape[0]
    rot = pods.rot_id

    sub = state.gather_rows(dirty_rows, dirty_valid)
    scores, feasible = score_pods(sub, pods, cfg)        # (P, D)
    clipped = jnp.clip(scores, 0, _SCORE_CLIP)
    # .max (OR), not .set: padded dirty_rows entries default to row 0
    # with valid=False, and a duplicate-index .set scatter is
    # order-undefined — it could erase row 0's genuine dirty bit
    dirty_mask = jnp.zeros(n, bool).at[dirty_rows].max(dirty_valid)
    stale_score = jnp.where(dirty_mask[cache.cand_node], -1,
                            cache.cand_score)

    splits = _stratum_splits(k, len(strata))
    nodes_out, scores_out = [], []
    off = 0
    for sb, k_i in zip(strata, splits):
        if k_i == 0:
            continue
        seg_node = cache.cand_node[:, off:off + k_i]
        seg_score = stale_score[:, off:off + k_i]
        off += k_i
        dkey, dtb = _rank_parts(scores, feasible, sb, rot,
                                node_ids=dirty_rows, n_total=n)
        if k_i < d:
            dval, idx = _topk_by_rank(dkey, dtb, k_i, n)
            d_node = dirty_rows[idx]
            d_score = jnp.where(
                dval >= 0, jnp.take_along_axis(clipped, idx, axis=1), -1)
        else:
            dval = dkey
            d_node = jnp.broadcast_to(dirty_rows[None, :], dkey.shape)
            d_score = jnp.where(dval >= 0, clipped, -1)
        c_key = _candidate_keys(seg_score, seg_node, rot, sb, n)
        m_key = jnp.concatenate([c_key, dval], axis=1)
        m_node = jnp.concatenate([seg_node, d_node], axis=1)
        m_score = jnp.concatenate([seg_score, d_score], axis=1)
        mval, midx = _topk_by_rank(
            m_key, _candidate_tb(m_node, rot, n), k_i, n)
        nodes_out.append(jnp.take_along_axis(m_node, midx, axis=1))
        scores_out.append(jnp.where(
            mval >= 0, jnp.take_along_axis(m_score, midx, axis=1), -1))

    cand_node = (jnp.concatenate(nodes_out, axis=1)
                 if len(nodes_out) > 1 else nodes_out[0])
    cand_score = (jnp.concatenate(scores_out, axis=1)
                  if len(scores_out) > 1 else scores_out[0])
    cand_key = _candidate_keys(cand_score, cand_node, rot, strata[0], n)
    return cand_key, CandidateCache(cand_key, cand_node, cand_score)


def scatter_candidate_rows(
    cache: CandidateCache,
    rows: jnp.ndarray,        # (S,) int32; out-of-range padding drops
    src_key: jnp.ndarray,     # (S, k)
    src_node: jnp.ndarray,
    src_score: jnp.ndarray,
) -> CandidateCache:
    """Overwrite the fully-rescored (dirty-pod) rows into the cache —
    the compacted select's output scattered back to global batch rows."""
    return CandidateCache(
        cand_key=cache.cand_key.at[rows].set(src_key, mode="drop"),
        cand_node=cache.cand_node.at[rows].set(src_node, mode="drop"),
        cand_score=cache.cand_score.at[rows].set(src_score, mode="drop"),
    )


def assign_round_pass(
    state: ClusterState,
    pods: PodBatch,
    quota: QuotaDeviceState | None,
    cand_key: jnp.ndarray,
    cand_node: jnp.ndarray,
    cfg: ScoringConfig,
    rounds: int = 12,
):
    """First solve pass over precomputed candidates, with the est-usage
    accumulation and quota recharge :func:`~koordinator_tpu.ops.gang.
    gang_assign` applies between passes — bit-identical to gang_assign's
    first pass over a GANGLESS batch (the incremental scheduler path only
    runs when the round has no gang pods).

    Returns (assignments, new_state, new_quota, est_accum)."""
    from koordinator_tpu.ops.assignment import pod_estimates

    a, new_state, _ = _assign_rounds(state, pods, quota, cand_key,
                                     cand_node, rounds)
    keep = a >= 0
    est = pod_estimates(pods, cfg)
    node = jnp.where(keep, a, 0)
    est_accum = jnp.zeros_like(state.node_usage).at[node].add(
        jnp.where(keep[:, None], est, 0))
    new_quota = quota
    if quota is not None:
        # the in-rounds quota feedback is discarded and recharged whole,
        # exactly as gang_assign does after rollback
        new_quota = charge_quota_batch(
            quota, pods.requests, pods.quota_id, keep, pods.non_preemptible)
    return a, new_state, new_quota, est_accum


def assign_followup_pass(
    state: ClusterState,
    est_accum: jnp.ndarray,
    pods: PodBatch,
    quota: QuotaDeviceState | None,
    cfg: ScoringConfig,
    k: int = 32,
    rounds: int = 12,
    spread_bits=(5, 15),
    method: str = "auto",
):
    """A later gang_assign pass over the (compacted) leftover pods:
    candidates re-selected against the est-augmented state, assignments
    committed into the UN-augmented accounting (gang_assign's rollback
    rebuild).  Candidate selection is row-independent and rot_id rides
    the compacted batch, so solving the compacted leftovers equals
    solving the full batch with everyone else masked invalid.

    Returns (assignments, new_state, new_quota, est_accum')."""
    from koordinator_tpu.ops.assignment import pod_estimates

    solve_state = state.replace(
        node_usage=state.node_usage + est_accum,
        node_agg_usage=state.node_agg_usage + est_accum)
    a, _, _ = batch_assign(solve_state, pods, cfg, quota, k=k,
                           rounds=rounds, spread_bits=spread_bits,
                           method=method)
    keep = (a >= 0) & pods.valid
    node = jnp.where(keep, a, 0)
    add = jnp.where(keep[:, None], pods.requests, 0)
    new_state = state.replace(
        node_requested=state.node_requested.at[node].add(add))
    est = pod_estimates(pods, cfg)
    est_accum = est_accum.at[node].add(jnp.where(keep[:, None], est, 0))
    new_quota = quota
    if quota is not None:
        new_quota = charge_quota_batch(
            quota, pods.requests, pods.quota_id, keep, pods.non_preemptible)
    return a, new_state, new_quota, est_accum
