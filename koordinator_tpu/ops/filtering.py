"""Feasibility masks: the Filter phase as boolean tensor algebra.

Replaces the reference's per-node Filter loop (parallelized over node chunks in
the upstream scheduler) with whole-matrix boolean ops:

- :func:`fit_mask` — NodeResourcesFit: every requested dimension fits into the
  node's request-free capacity. (Upstream plugin configured by koordinator's
  profiles; semantics from k8s noderesources.Fit.)
- :func:`usage_threshold_mask` — LoadAwareScheduling Filter
  (``pkg/scheduler/plugins/loadaware/load_aware.go:150``): node is
  unschedulable when round(estimatedUsage / allocatable * 100) exceeds the
  per-resource threshold; supports both instantaneous and aggregated-percentile
  usage inputs.
"""

from __future__ import annotations

import jax.numpy as jnp

MAX_SCALE = 100  # percentage scale; MaxNodeScore upstream


def fit_mask(free: jnp.ndarray, requests: jnp.ndarray) -> jnp.ndarray:
    """(N, R) free x (P, R) requests -> (P, N) bool: request fits entirely.

    Dimensions the pod does not request (req == 0) never exclude a node.
    """
    # req == 0 dims must not exclude a node even when free is negative there
    # (batch allocatable can shrink below what is already scheduled).
    fits = (requests[:, None, :] <= free[None, :, :]) | (requests[:, None, :] == 0)
    return jnp.all(fits, axis=-1)


def usage_threshold_mask(
    usage: jnp.ndarray,
    allocatable: jnp.ndarray,
    thresholds: jnp.ndarray,
    pod_estimated: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """LoadAware usage-threshold filter.

    Args:
      usage: (N, R) int32 estimated node usage (already includes assign-cache
        estimates of in-flight pods, per load_aware.go:150's estimatedUsed).
      allocatable: (N, R) int32.
      thresholds: (R,) int32 percentage thresholds; 0 = no threshold for dim
        (the reference only checks resources present in the threshold map).
      pod_estimated: optional (P, R) estimated usage of the pods being placed;
        when given the result is per-pod (P, N), else (N,).

    Returns (P, N) or (N,) bool — True = node passes.

    Parity note: usage percentage is round(est*100/total) compared with `>`
    (load_aware.go:326 ``usage := int64(math.Round(...)); if usage <= value``).
    Rounding is matched via (200*est + total) // (2*total).
    """
    total = allocatable  # (N, R)
    if pod_estimated is not None:
        est = usage[None, :, :] + pod_estimated[:, None, :]  # (P, N, R)
        total = total[None, :, :]
    else:
        est = usage

    # round(est*100/total) > thr, with round-half-up = floor((100e + t//2)/t).
    # The quotient itself is never needed — cross-multiplying gives the exact
    # same predicate with no division (the hot-loop win: this runs per
    # (pod, node, dim)):  floor(A/t) > thr  <=>  A >= (thr+1)*t.
    # int32-safe: A <= 100*est + t/2 < 2^31 and (thr+1)*t <= 101*MAX_QUANTITY
    # < 2^31 for the documented quantity bound (api/resources.py).
    a = MAX_SCALE * est + total // 2
    exceeded = (thresholds > 0) & (total > 0) & (a >= (thresholds + 1) * total)
    return ~jnp.any(exceeded, axis=-1)


def combine_masks(*masks: jnp.ndarray) -> jnp.ndarray:
    """AND together broadcastable feasibility masks."""
    out = masks[0]
    for m in masks[1:]:
        out = out & m
    return out
