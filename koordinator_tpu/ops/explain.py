"""Placement explainability: device-side reject-reason accounting.

``ops/filtering.py`` computes the per-(pod, node) reject masks on device
and ``combine_masks`` discards them — a pod that stays pending at
50k x 10,240 scale yields no answer to "which constraint killed it on
which nodes".  This module threads a compact reason taxonomy through the
same mask algebra and reduces it device-side into per-pod x per-reason
NODE COUNTS: an O(P·R_reasons) output folded out of the masks the solve
already computes, never materializing the (P, N) reason tensor on host.

Attribution is FIRST-FAIL in filter order (matching
``scheduler/diagnosis.explain_pod``): a node counts against exactly one
reason — resource fit (per dimension, first failing dim in global dim
order), then the usage threshold, then affinity/selector.  Invalid node
rows count separately.  Pod-level gates (elastic-quota admission, the
gang barrier, degraded-mode suspension) have no per-node mask: their
columns exist in the taxonomy for the scheduler to fill host-side when
it attributes a failure to them (``scheduler/scheduler.py`` Diagnose).

The kernel is cheap relative to a solve — masks plus one segment
reduction, no scoring, no top-k — and the scheduler only runs it over
the COMPACTED failed rows of a round, so explain-enabled rounds with a
healthy queue pay nothing (bench_stages.py's ``explain_*`` stages guard
the <5% overhead claim at the north-star shape).
"""

from __future__ import annotations

import jax.numpy as jnp

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS, ResourceDim
from koordinator_tpu.ops import scoring
from koordinator_tpu.state.cluster_state import ClusterState, PodBatch

# ---- reason taxonomy -------------------------------------------------------
#
# Stable column order of the (P, NUM_REASONS) counts tensor.  Do not
# reorder: dashboards, the metrics labels, and recorded explanations all
# key on these names.

REASON_NODE_INVALID = 0
#: per-dimension resource fit: column REASON_FIT_FIRST + ResourceDim
REASON_FIT_FIRST = 1
REASON_USAGE_THRESHOLD = 1 + NUM_RESOURCE_DIMS
REASON_AFFINITY = 2 + NUM_RESOURCE_DIMS
#: pod-level gates (host-filled; the device kernel leaves them zero)
REASON_QUOTA = 3 + NUM_RESOURCE_DIMS
REASON_GANG = 4 + NUM_RESOURCE_DIMS
REASON_DEGRADED = 5 + NUM_RESOURCE_DIMS
NUM_REASONS = 6 + NUM_RESOURCE_DIMS

REASON_NAMES: tuple[str, ...] = (
    "node_invalid",
    *(f"fit_{dim.name.lower()}" for dim in ResourceDim),
    "usage_threshold",
    "affinity",
    "quota",
    "gang_barrier",
    "degraded_suspended",
)
assert len(REASON_NAMES) == NUM_REASONS

#: columns the device kernel fills (everything before the pod-level gates)
NODE_REASONS = REASON_NAMES[:REASON_QUOTA]


def fit_first_fail(free: jnp.ndarray, requests: jnp.ndarray) -> jnp.ndarray:
    """(P, N, R) bool: dimension d is the FIRST dim (global dim order)
    where the pod's request does not fit the node's free capacity.

    At most one True per (pod, node); all-False rows fit every dim.
    The complement of ``filtering.fit_mask`` attributed per-dim.
    """
    dim_ok = (requests[:, None, :] <= free[None, :, :]) | (
        requests[:, None, :] == 0)
    fails = ~dim_ok
    # fails before this dim (exclusive running count): first fail <=> no
    # earlier dim failed
    prior = jnp.cumsum(fails, axis=-1) - fails
    return fails & (prior == 0)


def explain_counts(
    state: ClusterState, pods: PodBatch, cfg,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Device-side reject-reason accounting for a pod batch.

    Returns ``(counts, feasible)``: counts is (P, NUM_REASONS) int32 —
    per pod, how many nodes each reason eliminated (first-fail
    attribution; pod-level gate columns stay zero) — and feasible is
    (P,) int32, the nodes that survived every filter.  Row sums satisfy
    ``feasible + sum(node-reason counts) == node capacity`` for valid
    pods; invalid pod rows are all zero.

    ``cfg`` is a :class:`~koordinator_tpu.ops.assignment.ScoringConfig`
    (typed loosely to avoid the circular import).  The (P, N, R) mask
    intermediates live only inside the jit — the host only ever sees the
    O(P·NUM_REASONS) reduction.
    """
    from koordinator_tpu.ops.assignment import _threshold_mask

    pod_est = scoring.estimate_pod_usage_by_band(
        pods.requests, cfg.estimator_factors, cfg.estimator_defaults)
    valid_n = state.node_valid                          # (N,)
    pod_valid = pods.valid                              # (P,)
    base = valid_n[None, :] & pod_valid[:, None]        # (P, N)

    ff = fit_first_fail(state.free, pods.requests)      # (P, N, R)
    fit = ~jnp.any(ff, axis=-1)                         # (P, N)
    thr = _threshold_mask(cfg, state.node_usage, state.node_agg_usage,
                          state.node_allocatable, pod_est)
    aff = pods.feasible_rows(state)

    fit_counts = jnp.sum((base & ~fit)[:, :, None] & ff, axis=1)  # (P, R)
    thr_fail = jnp.sum(base & fit & ~thr, axis=1)                 # (P,)
    aff_fail = jnp.sum(base & fit & thr & ~aff, axis=1)
    feasible = jnp.sum(base & fit & thr & aff, axis=1)
    invalid = jnp.where(pod_valid, jnp.sum(~valid_n), 0)

    counts = jnp.concatenate(
        [
            invalid[:, None],
            fit_counts,
            thr_fail[:, None],
            aff_fail[:, None],
            jnp.zeros((pods.capacity, 3), jnp.int32),   # quota/gang/degraded
        ],
        axis=1,
    ).astype(jnp.int32)
    return counts, feasible.astype(jnp.int32)


def decompose_scores(
    state: ClusterState, pods: PodBatch, cfg, cand_node: jnp.ndarray,
) -> dict[str, jnp.ndarray]:
    """Per-term score decomposition at the given candidate nodes.

    ``cand_node`` is (P, K) int32 node rows (a pod's winning node and/or
    its top-k candidates).  Returns a dict of (P, K) int32 arrays — the
    raw per-plugin scores (``loadaware``, ``fitplus``, ``scarce``) out of
    :mod:`ops/scoring` plus their weighted ``total`` — bit-identical to
    the composite ``score_pods`` computes at the same (pod, node) pairs,
    so an explanation's decomposition provably sums to the score the
    solve ranked on.
    """
    req = pods.requests                                  # (P, R)
    pod_est = scoring.estimate_pod_usage_by_band(
        req, cfg.estimator_factors, cfg.estimator_defaults)
    alloc = state.node_allocatable[cand_node]            # (P, K, R)
    requested = state.node_requested[cand_node]
    usage = state.node_usage[cand_node]

    la = scoring.loadaware_score(
        usage + pod_est[:, None, :], alloc,
        cfg.loadaware_resource_weights, cfg.loadaware_dominant_weight)

    # NodeResourcesFitPlus at gathered (P, K, R) node rows — the same
    # math as scoring.fitplus_score, whose signature is (N, R)-shaped
    combined = requested + req[:, None, :]
    least = scoring.least_requested_score(combined, alloc)
    most = scoring.most_requested_score(combined, alloc)
    per_res = jnp.where(cfg.fitplus_most_allocated, most, least)
    req_mask = (req > 0)[:, None, :]
    w = jnp.where(req_mask, cfg.fitplus_resource_weights.astype(jnp.int32), 0)
    num = jnp.sum(per_res * w, axis=-1)
    den = jnp.sum(w, axis=-1)
    fp = jnp.where(den > 0,
                   scoring.exact_floordiv(num, jnp.maximum(den, 1)),
                   scoring.MAX_NODE_SCORE)

    # ScarceResourceAvoidance at gathered rows
    node_has = alloc > 0
    pod_wants = (req > 0)[:, None, :]
    diff = node_has & ~pod_wants
    inter = diff & cfg.scarce_dims
    n_diff = jnp.sum(diff, axis=-1).astype(jnp.int32)
    n_inter = jnp.sum(inter, axis=-1).astype(jnp.int32)
    sc = scoring.exact_floordiv(
        (n_diff - n_inter) * scoring.MAX_NODE_SCORE, jnp.maximum(n_diff, 1))
    sc = jnp.where((n_diff == 0) | (n_inter == 0), scoring.MAX_NODE_SCORE, sc)

    total = (la * cfg.loadaware_plugin_weight
             + fp * cfg.fitplus_plugin_weight
             + sc * cfg.scarce_plugin_weight)
    return {"loadaware": la, "fitplus": fp, "scarce": sc, "total": total}
