"""Batched solver kernels: the TPU replacement for the reference's hot loops.

Where the reference runs per-pod x per-node Go plugin callbacks
(``frameworkext/framework_extender.go`` RunFilterPlugins/RunScorePlugins), every
kernel here consumes the whole (pods x nodes x dims) problem at once:

- ``filtering``  -- feasibility masks (NodeResourcesFit + loadaware thresholds)
- ``scoring``    -- loadaware / fitplus / scarce-resource scorers
- ``assignment`` -- greedy sequential assignment with capacity feedback
- ``quota``      -- hierarchical elastic-quota water-filling (Hamilton method)
- ``gang``       -- gang all-or-nothing grouped assignment
"""
