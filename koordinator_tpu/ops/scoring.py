"""Score kernels with reference-parity integer semantics.

Each scorer is written over the full (pods x nodes) problem; the per-node Go
functions they replace are cited inline. MaxNodeScore = 100 as upstream.

All division is integer floor division on int32, matching the reference's
int64 ``/`` on non-negative operands.
"""

from __future__ import annotations

import jax.numpy as jnp

MAX_NODE_SCORE = 100


def exact_floordiv(num: jnp.ndarray, den: jnp.ndarray,
                   inv: jnp.ndarray | None = None) -> jnp.ndarray:
    """Exact ``num // den`` for non-negative int32 num, positive den, with
    quotients below ~2^20 (every score/percent here is <= ~1e5).

    Generic int32 division lowers to a long per-element op sequence on TPU —
    it dominated the whole Filter+Score kernel (~25x the rest combined). A
    float32 estimate is within +-0.2 of the true quotient in this domain
    (q * 3*2^-24 < 1 for q < 2^20), so one f32 multiply/divide plus a
    single-multiply integer correction reproduces floor division bit-exactly.

    Pass ``inv`` = 1/den as float32 (precomputed per node, reused across the
    pod axis) to replace the f32 divide with a multiply.
    """
    if inv is None:
        q0 = (num.astype(jnp.float32) / den.astype(jnp.float32)).astype(jnp.int32)
    else:
        q0 = (num.astype(jnp.float32) * inv).astype(jnp.int32)
    # Correction products run in uint32 (int64 is x64-gated): num <= 2^31-1
    # and den <= MAX_QUANTITY, so prod1 + den <= num + den < 2^32.
    num_u = num.astype(jnp.uint32)
    den_u = den.astype(jnp.uint32)
    q_u = jnp.maximum(q0, 0).astype(jnp.uint32)
    prod = q_u * den_u
    over = prod > num_u                      # estimate one too high
    q_u = q_u - over
    prod = jnp.where(over, prod - den_u, prod)
    q_u = q_u + (prod + den_u <= num_u)      # estimate one too low
    return q_u.astype(jnp.int32)


def least_used_score(used: jnp.ndarray, capacity: jnp.ndarray,
                     inv_capacity: jnp.ndarray | None = None) -> jnp.ndarray:
    """(capacity-used)*100/capacity; 0 when capacity==0 or used>capacity.

    Parity: pkg/scheduler/plugins/loadaware/load_aware.go:368 leastUsedScore.
    inv_capacity: optional precomputed 1/capacity float32 (see exact_floordiv).
    """
    ok = (capacity > 0) & (used <= capacity)
    safe_cap = jnp.maximum(capacity, 1)
    return jnp.where(
        ok,
        exact_floordiv(jnp.maximum(capacity - used, 0) * MAX_NODE_SCORE,
                       safe_cap, inv=inv_capacity),
        0,
    )


def most_requested_score(requested: jnp.ndarray, capacity: jnp.ndarray) -> jnp.ndarray:
    """min(requested, capacity)*100/capacity; 0 when capacity==0.

    Parity: noderesourcefitplus/node_resource_fit_plus_utils.go:36 — requested
    beyond capacity is clamped (an overcommitted dim scores the full 100).
    """
    clamped = jnp.minimum(requested, capacity)
    safe_cap = jnp.maximum(capacity, 1)
    return jnp.where(
        capacity > 0, exact_floordiv(clamped * MAX_NODE_SCORE, safe_cap), 0
    )


def least_requested_score(requested: jnp.ndarray, capacity: jnp.ndarray) -> jnp.ndarray:
    """(capacity-requested)*100/capacity; 0 when capacity==0 or requested>capacity.

    Parity: noderesourcefitplus/node_resource_fit_plus_utils.go:47.
    """
    return least_used_score(requested, capacity)


def loadaware_score(
    used: jnp.ndarray,
    allocatable: jnp.ndarray,
    weights: jnp.ndarray,
    dominant_weight: int = 0,
) -> jnp.ndarray:
    """LoadAwareScheduling scorer: weighted least-used + dominant-resource term.

    Parity: load_aware.go:347 loadAwareSchedulingScorer —
      nodeScore = sum_i w_i * leastUsed_i  +  dw * min_i leastUsed_i
      score     = nodeScore / (sum_i w_i + dw)
    The min runs over configured resources (w_i > 0 here); with dw != 0 the
    dominant score starts at MaxNodeScore (so no configured resources -> 100).

    Args:
      used: (..., N, R) estimated used (node usage + estimated pod usage).
      allocatable: (N, R) or broadcastable.
      weights: (R,) int32; 0 = resource not configured.
      dominant_weight: scalar int.

    Returns (..., N) int32 scores in [0, 100].
    """
    # reciprocal computed once per (node, dim), reused across the pod axis
    inv = 1.0 / jnp.maximum(allocatable, 1).astype(jnp.float32)
    per_res = least_used_score(used, allocatable, inv)  # (..., N, R)
    w = weights.astype(jnp.int32)
    dw = jnp.asarray(dominant_weight, dtype=jnp.int32)
    configured = w > 0
    dominant = jnp.min(jnp.where(configured, per_res, MAX_NODE_SCORE), axis=-1)
    # dw == 0 contributes nothing to either term, so the "only if dominant
    # weight set" branch of the reference folds into one expression.
    node_score = jnp.sum(per_res * w, axis=-1) + dominant * dw
    weight_sum = jnp.sum(w) + dw
    return jnp.where(
        weight_sum > 0, exact_floordiv(node_score, jnp.maximum(weight_sum, 1)), 0
    )


def fitplus_score(
    requested: jnp.ndarray,
    allocatable: jnp.ndarray,
    pod_requests: jnp.ndarray,
    weights: jnp.ndarray,
    most_allocated: jnp.ndarray,
) -> jnp.ndarray:
    """NodeResourcesFitPlus: per-resource least/most-allocated strategy weights.

    Parity: noderesourcefitplus/node_resource_fit_plus_utils.go:58
    resourceScorer — for each resource the POD requests (req > 0):
      score_r = strategy_r(nodeRequested_r + podRequest_r, allocatable_r) * w_r
      final   = sum_r score_r / sum_r w_r      (only over requested resources)

    Args:
      requested: (N, R) node requested (without the pod).
      allocatable: (N, R).
      pod_requests: (P, R).
      weights: (R,) int32 per-resource strategy weight.
      most_allocated: (R,) bool — True = MostAllocated strategy, else Least.

    Returns (P, N) int32.
    """
    combined = requested[None, :, :] + pod_requests[:, None, :]  # (P, N, R)
    least = least_requested_score(combined, allocatable[None])
    most = most_requested_score(combined, allocatable[None])
    per_res = jnp.where(most_allocated, most, least)  # (P, N, R)

    req_mask = pod_requests[:, None, :] > 0  # (P, 1, R)
    w = jnp.where(req_mask, weights.astype(jnp.int32), 0)  # (P, 1, R)
    num = jnp.sum(per_res * w, axis=-1)  # (P, N)
    den = jnp.sum(w, axis=-1)  # (P, 1)
    # No weighted requested resources -> MaxNodeScore, per
    # node_resource_fit_plus_utils.go resourceScorer's weightSum==0 branch.
    return jnp.where(den > 0, exact_floordiv(num, jnp.maximum(den, 1)), MAX_NODE_SCORE)


def scarce_resource_score(
    pod_requests: jnp.ndarray,
    node_allocatable: jnp.ndarray,
    scarce_dims: jnp.ndarray,
) -> jnp.ndarray:
    """ScarceResourceAvoidance: penalize nodes whose scarce resources go unused.

    Parity: scarceresourceavoidance/scarce_resource_avoidance.go:89,158 —
      diff      = node resource types NOT requested by the pod
      intersect = diff ∩ configured scarce types
      score     = (|diff| - |intersect|) * 100 / |diff|, or 100 if either empty.

    Args:
      pod_requests: (P, R).
      node_allocatable: (N, R).
      scarce_dims: (R,) bool — configured scarce resource types.

    Returns (P, N) int32.
    """
    node_has = node_allocatable > 0  # (N, R)
    pod_wants = pod_requests > 0  # (P, R)
    diff = node_has[None, :, :] & ~pod_wants[:, None, :]  # (P, N, R)
    inter = diff & scarce_dims
    n_diff = jnp.sum(diff, axis=-1).astype(jnp.int32)
    n_inter = jnp.sum(inter, axis=-1).astype(jnp.int32)
    score = exact_floordiv((n_diff - n_inter) * MAX_NODE_SCORE, jnp.maximum(n_diff, 1))
    return jnp.where((n_diff == 0) | (n_inter == 0), MAX_NODE_SCORE, score)


def estimate_pod_usage(
    pod_requests: jnp.ndarray,
    scaling_factors_pct: jnp.ndarray,
    default_request: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """LoadAware DefaultEstimator: estimated usage = round(request * factor/100).

    Parity: loadaware/estimator/default_estimator.go:74-121 — requests are
    scaled by per-resource percentage factors; pods with zero cpu/memory
    requests estimate at defaults (250 mcore / 200 MiB).

    Args:
      pod_requests: (P, R) int32.
      scaling_factors_pct: (R,) int32 percent factors (e.g. cpu 85, memory 70).
      default_request: optional (R,) int32 used where request == 0.

    Returns (P, R) int32.
    """
    # round(req*f/100) = (100*req*f/100 + 50)/100; keep the intermediate at
    # req*f (int32-safe for req < 2^31/100 with pct factors <= 100).
    scaled = (pod_requests * scaling_factors_pct + 50) // 100
    if default_request is not None:
        # zero-request dims estimate at the (unscaled) default, per
        # default_estimator.go:97-102.
        scaled = jnp.where((pod_requests == 0) & (default_request > 0),
                           default_request, scaled)
    return scaled


def estimate_pod_usage_by_band(
    pod_requests: jnp.ndarray,
    scaling_factors_pct: jnp.ndarray,
    default_request: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Band-translated usage estimate: batch/mid requests count as physical use.

    Parity: default_estimator.go:74-83 — the estimator translates cpu/memory by
    the pod's priority class (``TranslateResourceNameByPriorityClass``), so a
    batch pod's ``batch-cpu`` request estimates *physical* CPU usage. A pod
    requests cpu in exactly one band's dims, so summing the bands recovers the
    translated request; the estimate lands in the physical CPU/MEMORY dims
    (usage thresholds and loadaware scoring compare against physical usage).
    """
    from koordinator_tpu.api.resources import (
        BATCH_DIMS, MID_DIMS, ResourceDim,
    )

    cpu_eff = (
        pod_requests[..., ResourceDim.CPU]
        + pod_requests[..., ResourceDim.BATCH_CPU]
        + pod_requests[..., ResourceDim.MID_CPU]
    )
    mem_eff = (
        pod_requests[..., ResourceDim.MEMORY]
        + pod_requests[..., ResourceDim.BATCH_MEMORY]
        + pod_requests[..., ResourceDim.MID_MEMORY]
    )
    translated = (
        pod_requests
        .at[..., ResourceDim.CPU].set(cpu_eff)
        .at[..., ResourceDim.MEMORY].set(mem_eff)
    )
    for d in (*BATCH_DIMS, *MID_DIMS):
        translated = translated.at[..., d].set(0)
    return estimate_pod_usage(translated, scaling_factors_pct, default_request)
