"""Batched scheduling: score the whole (pods x nodes) problem, then assign.

Two entry points:

- :func:`score_pods` — the fully-parallel Score()/Filter() replacement: one
  shot over the (P, N) matrix, no capacity feedback between pods. This is the
  kernel the Go/py scheduler shell calls for single-pod cycles (P=1..k) and the
  benchmark target (BASELINE.md: batched Score at 1k-10k nodes).

- :func:`greedy_assign` — sequential greedy assignment with capacity feedback
  via ``lax.scan`` in priority order: the tensor equivalent of running the
  reference's scheduleOne loop over a whole pending queue. Each step re-filters
  and re-scores against the updated free capacity, exactly as the reference's
  snapshot would after each binding.

The scoring pipeline composes the koordinator scheduler profile's score
plugins with their weights (cmd/koord-scheduler/main.go:47-58 registry;
weights from the scheduler profile):
  final = la_w * LoadAware + fp_w * NodeResourcesFitPlus + sc_w * ScarceResourceAvoidance
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS, ResourceDim
from koordinator_tpu.ops import filtering, scoring
from koordinator_tpu.quota.admission import charge_quota, quota_admission_mask
from koordinator_tpu.state.cluster_state import ClusterState, PodBatch


@struct.dataclass
class ScoringConfig:
    """Traced pytree of plugin weights/args (scheduler-profile equivalent)."""

    # LoadAwareScheduling args (apis/config/types.go LoadAwareSchedulingArgs)
    loadaware_resource_weights: jax.Array  # (R,) int32
    loadaware_dominant_weight: jax.Array   # () int32
    loadaware_plugin_weight: jax.Array     # () int32
    usage_thresholds: jax.Array            # (R,) int32 pct, 0 = unchecked
    agg_usage_thresholds: jax.Array        # (R,) int32 pct, 0 = unchecked
    estimator_factors: jax.Array           # (R,) int32 pct
    estimator_defaults: jax.Array          # (R,) int32

    # NodeResourcesFitPlus args
    fitplus_resource_weights: jax.Array    # (R,) int32
    fitplus_most_allocated: jax.Array      # (R,) bool
    fitplus_plugin_weight: jax.Array       # () int32

    # ScarceResourceAvoidance args
    scarce_dims: jax.Array                 # (R,) bool
    scarce_plugin_weight: jax.Array        # () int32

    @classmethod
    def default(cls) -> "ScoringConfig":
        r = NUM_RESOURCE_DIMS
        la_w = jnp.zeros(r, jnp.int32).at[ResourceDim.CPU].set(1).at[ResourceDim.MEMORY].set(1)
        factors = (
            jnp.full(r, 100, jnp.int32)
            .at[ResourceDim.CPU].set(85)      # DefaultEstimatedScalingFactors
            .at[ResourceDim.MEMORY].set(70)
        )
        defaults = (
            jnp.zeros(r, jnp.int32)
            .at[ResourceDim.CPU].set(250)     # DefaultMilliCPURequest
            .at[ResourceDim.MEMORY].set(200)  # DefaultMemoryRequest (MiB units)
        )
        fp_w = jnp.zeros(r, jnp.int32).at[ResourceDim.CPU].set(1).at[ResourceDim.MEMORY].set(1)
        return cls(
            loadaware_resource_weights=la_w,
            loadaware_dominant_weight=jnp.int32(0),
            loadaware_plugin_weight=jnp.int32(1),
            usage_thresholds=jnp.zeros(r, jnp.int32)
            .at[ResourceDim.CPU].set(65)      # defaultNodeCPUUsageThreshold
            .at[ResourceDim.MEMORY].set(95),
            agg_usage_thresholds=jnp.zeros(r, jnp.int32),
            estimator_factors=factors,
            estimator_defaults=defaults,
            fitplus_resource_weights=fp_w,
            fitplus_most_allocated=jnp.zeros(r, bool),
            fitplus_plugin_weight=jnp.int32(1),
            scarce_dims=jnp.zeros(r, bool).at[ResourceDim.GPU].set(True),
            scarce_plugin_weight=jnp.int32(0),
        )


def _composite_score(
    cfg: ScoringConfig,
    allocatable: jnp.ndarray,   # (N, R)
    requested: jnp.ndarray,     # (N, R)
    est_usage: jnp.ndarray,     # (N, R) node usage + in-flight estimates
    pod_requests: jnp.ndarray,  # (P, R)
    pod_estimated: jnp.ndarray, # (P, R)
) -> jnp.ndarray:
    """(P, N) weighted sum of score plugins."""
    la = scoring.loadaware_score(
        est_usage[None, :, :] + pod_estimated[:, None, :],
        allocatable[None, :, :],
        cfg.loadaware_resource_weights,
        cfg.loadaware_dominant_weight,
    )
    fp = scoring.fitplus_score(
        requested, allocatable, pod_requests,
        cfg.fitplus_resource_weights, cfg.fitplus_most_allocated,
    )
    sc = scoring.scarce_resource_score(pod_requests, allocatable, cfg.scarce_dims)
    return (
        la * cfg.loadaware_plugin_weight
        + fp * cfg.fitplus_plugin_weight
        + sc * cfg.scarce_plugin_weight
    )


def _threshold_mask(cfg, usage, agg_usage, allocatable, pod_est):
    """LoadAware Filter threshold selection: the aggregated-percentile policy,
    when configured, REPLACES the instantaneous thresholds (load_aware.go:150
    checks one or the other, never both)."""
    inst = filtering.usage_threshold_mask(
        usage, allocatable, cfg.usage_thresholds, pod_est
    )
    agg = filtering.usage_threshold_mask(
        agg_usage, allocatable, cfg.agg_usage_thresholds, pod_est
    )
    agg_enabled = jnp.any(cfg.agg_usage_thresholds > 0)
    return jnp.where(agg_enabled, agg, inst)


def pod_estimates(pods: PodBatch, cfg: ScoringConfig) -> jnp.ndarray:
    """(P, R) estimated usage per pod (the LoadAware estimator) — shared
    by gang_assign's inter-pass est accumulation and the incremental
    solve's pass functions, so the two pass loops cannot drift."""
    return scoring.estimate_pod_usage_by_band(
        pods.requests, cfg.estimator_factors, cfg.estimator_defaults
    )


def score_pods(
    state: ClusterState, pods: PodBatch, cfg: ScoringConfig
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """One-shot batched Filter+Score (no capacity feedback).

    Returns (scores, feasible): (P, N) int32 and (P, N) bool.
    """
    pod_est = scoring.estimate_pod_usage_by_band(
        pods.requests, cfg.estimator_factors, cfg.estimator_defaults
    )
    free = state.free
    feasible = filtering.combine_masks(
        filtering.fit_mask(free, pods.requests),
        _threshold_mask(cfg, state.node_usage, state.node_agg_usage,
                        state.node_allocatable, pod_est),
        pods.feasible_rows(state),
        state.node_valid[None, :],
        pods.valid[:, None],
    )
    scores = _composite_score(
        cfg,
        state.node_allocatable,
        state.node_requested,
        state.node_usage,
        pods.requests,
        pod_est,
    )
    return scores, feasible


def _greedy_scan(
    state: ClusterState,
    pods: PodBatch,
    cfg: ScoringConfig,
    quota=None,
    rsv=None,
    match=None,
    rsv_boost: int = 10_000,
):
    """Shared sequential-assignment scan (the single source of truth for both
    plain and reservation-aware greedy assignment).

    Returns (assignments, rsv_choice, new_state, new_rsv, new_quota); the
    reservation outputs are None when ``rsv`` is None.
    """
    from koordinator_tpu.ops.reservation import (
        allocate_from_reservation,
        nominate_reservation,
        reservation_fit,
        reservation_node_mask,
    )

    if match is not None:
        match = jnp.asarray(match)  # host producers hand over np.ndarray

    order = jnp.lexsort((jnp.arange(pods.capacity), -pods.priority))

    pod_est_all = scoring.estimate_pod_usage_by_band(
        pods.requests, cfg.estimator_factors, cfg.estimator_defaults
    )

    def step(carry, idx):
        # est_added accumulates in-flight pods' estimated usage (the
        # reference's pod-assign cache) on top of whichever usage base the
        # threshold policy selects.
        requested, est_added, cur_rsv, qstate = carry
        req = pods.requests[idx]          # (R,)
        pod_est = pod_est_all[idx]        # (R,)
        valid = pods.valid[idx]

        free = jnp.where(
            state.node_valid[:, None], state.node_allocatable - requested, 0
        )
        fits = jnp.all((req[None, :] <= free) | (req[None, :] == 0), axis=-1)
        if cur_rsv is not None:
            fits_v = reservation_fit(cur_rsv, free, req[None, :], match[idx][None])[0]
            via_rsv = reservation_node_mask(fits_v[None], cur_rsv, state.capacity)[0]
            fits = fits | via_rsv
        feasible = (
            fits
            & _threshold_mask(
                cfg,
                state.node_usage + est_added,
                state.node_agg_usage + est_added,
                state.node_allocatable,
                pod_est[None, :],
            )[0]
            & pods.feasible_row(state, idx)
            & state.node_valid
            & valid
        )
        if qstate is not None:
            admitted = quota_admission_mask(
                qstate, req[None, :], pods.quota_id[idx][None],
                pods.non_preemptible[idx][None],
            )[0]
            feasible = feasible & admitted

        scores = _composite_score(
            cfg, state.node_allocatable, requested,
            state.node_usage + est_added,
            req[None, :], pod_est[None, :],
        )[0]
        if cur_rsv is not None:
            scores = scores + jnp.where(via_rsv, rsv_boost, 0)
        masked = jnp.where(feasible, scores, -1)
        best = jnp.argmax(masked)
        assigned = masked[best] >= 0
        node = jnp.where(assigned, best, -1)

        if cur_rsv is not None:
            r_idx = nominate_reservation(fits_v[None], cur_rsv, node[None])[0]
            r_idx = jnp.where(assigned, r_idx, -1)
            cur_rsv, spill = allocate_from_reservation(cur_rsv, r_idx, req)
            add = jnp.where(assigned, spill, 0)
        else:
            r_idx = jnp.int32(-1)
            add = jnp.where(assigned, req, 0)
        add_est = jnp.where(assigned, pod_est, 0)
        requested = requested.at[best].add(add)
        est_added = est_added.at[best].add(add_est)
        if qstate is not None:
            qstate = charge_quota(
                qstate, jnp.where(assigned, req, 0),
                jnp.where(assigned, pods.quota_id[idx], -1),
                non_preemptible=pods.non_preemptible[idx],
            )
        return (requested, est_added, cur_rsv, qstate), (node, r_idx)

    (requested, _, new_rsv, new_quota), (nodes_in_order, rsv_in_order) = jax.lax.scan(
        step,
        (state.node_requested, jnp.zeros_like(state.node_usage), rsv, quota),
        order,
    )
    assignments = jnp.full(pods.capacity, -1, jnp.int32).at[order].set(nodes_in_order)
    rsv_choice = (
        jnp.full(pods.capacity, -1, jnp.int32).at[order].set(rsv_in_order)
        if rsv is not None
        else None
    )
    new_state = state.replace(node_requested=requested)
    return assignments, rsv_choice, new_state, new_rsv, new_quota


def greedy_assign(
    state: ClusterState,
    pods: PodBatch,
    cfg: ScoringConfig,
    quota=None,
):
    """Assign a whole pending batch sequentially in priority order.

    Returns (assignments, new_state, new_quota). new_quota is None unless a
    :class:`~koordinator_tpu.quota.QuotaDeviceState` is given, in which case
    each pod must also pass the elastic-quota admission check and Reserve-time
    quota accounting feeds back within the batch.

    assignments is (P,) int32 node index per pod (original batch order),
    -1 = unschedulable; new_state carries the updated node_requested
    accounting (Reserve semantics).

    Determinism: ties break toward the lowest node index (the reference's
    selectHost randomizes among maxima; we fix the choice for reproducibility).
    """
    assignments, _, new_state, _, new_quota = _greedy_scan(
        state, pods, cfg, quota=quota
    )
    return assignments, new_state, new_quota
