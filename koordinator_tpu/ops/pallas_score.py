"""Fused Filter+Score+top-k as a Pallas TPU kernel.

``batch_assign`` currently runs three XLA stages: ``score_pods`` (which
materializes the (P, N) int32 score tensor to HBM — 2 GB at the north-star
shape), ``_ranked_scores`` (another (P, N)), and ``lax.top_k``.  This kernel
streams instead: each program owns a tile of pods, walks the node axis in
VMEM-sized chunks, computes the ranked key for the chunk in registers, and
folds it into a running per-pod top-k — the (P, N) intermediates never
touch HBM, only the (P, k) winners do.

Semantics are IDENTICAL to ``lax.top_k(_ranked_scores(*score_pods(...)), k)``
(same scorer formulas, same integer floor-division trick, same rotated
tie-break, same lowest-index-wins tie order) and are asserted bit-exact
against that reference in tests/test_pallas_score.py via interpret mode.

Layouts are transposed (R leading) so pods/nodes ride the 128-lane axis;
R (=10) unrolls as python loops.  The selector-class feasibility gather
``selector_mask[:, node_class]`` becomes a one-hot matmul on the MXU.

Reference parity anchors are the same as ops/scoring.py (load_aware.go:347,
node_resource_fit_plus_utils.go:58, scarce_resource_avoidance.go:89,
load_aware.go:326 thresholds).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from koordinator_tpu.ops.assignment import ScoringConfig
from koordinator_tpu.ops.batch_assign import _TB_BITS, _SCORE_CLIP
from koordinator_tpu.ops.scoring import MAX_NODE_SCORE, exact_floordiv
from koordinator_tpu.state.cluster_state import ClusterState, PodBatch

from koordinator_tpu.ops.filtering import MAX_SCALE

def _floordiv(num, den, den_pos):
    """exact_floordiv guarded for den<=0 rows (returns 0 there)."""
    safe = jnp.maximum(den, 1)
    return jnp.where(den_pos, exact_floordiv(jnp.maximum(num, 0), safe), 0)


def _score_topk_kernel(
    # pod tile refs (blocked over P)
    podreq_ref,      # (R, TP) int32
    podest_ref,      # (R, TP) int32
    podvalid_ref,    # (1, TP) int32
    sel_ref,         # (TP, C) int32 0/1
    # full node refs
    alloc_ref,       # (R, N) int32
    reqd_ref,        # (R, N) int32
    usage_ref,       # (R, N) int32
    agg_ref,         # (R, N) int32
    nvalid_ref,      # (1, N) int32
    nclass_ref,      # (1, N) int32
    # cfg refs
    la_w_ref,        # (1, R) int32 loadaware weights
    fp_w_ref,        # (1, R) int32 fitplus weights
    fp_most_ref,     # (1, R) int32 bool
    scarce_ref,      # (1, R) int32 bool
    thr_ref,         # (1, R) int32 usage thresholds
    agg_thr_ref,     # (1, R) int32 aggregated thresholds
    scalars_ref,     # (1, 4) int32: [dominant_w, la_plugin_w, fp_plugin_w,
                     #               scarce_plugin_w]
    # outputs
    out_val_ref,     # (TP, K) int32
    out_idx_ref,     # (TP, K) int32
    *,
    n_chunk: int,
    k: int,
    r_dims: int,
    spread_bits: int,
):
    tp = podreq_ref.shape[1]
    n = alloc_ref.shape[1]
    tile = pl.program_id(0)

    dom_w = scalars_ref[0, 0]
    la_pw = scalars_ref[0, 1]
    fp_pw = scalars_ref[0, 2]
    sc_pw = scalars_ref[0, 3]
    agg_enabled = jnp.any(agg_thr_ref[0, :] > 0)

    pod_valid = podvalid_ref[0, :] > 0                    # (TP,)
    # fitplus per-pod weight sum over requested dims (den), (TP,)
    fp_den = jnp.zeros((tp,), jnp.int32)
    la_wsum = jnp.int32(0)
    for r in range(r_dims):
        fp_den = fp_den + jnp.where(podreq_ref[r, :] > 0, fp_w_ref[0, r], 0)
        la_wsum = la_wsum + la_w_ref[0, r]
    la_den = la_wsum + dom_w                              # scalar
    sel = sel_ref[:, :].astype(jnp.float32)               # (TP, C)
    c_cap = sel.shape[1]

    # rotated tie-break offsets for this tile's global pod rows
    pod_ids = tile * tp + jax.lax.broadcasted_iota(jnp.int32, (tp, 1), 0)
    rot = pod_ids * 7919                                  # (TP, 1)

    run_val = jnp.full((tp, k), -1, jnp.int32)
    # sentinel indices are UNIQUE negatives: the extract-max fold removes
    # exactly one column per pass (equal (val, idx) pairs would be wiped
    # together, collapsing the pool into -2s); sanitized to 0 on output
    run_idx = -1 - jax.lax.broadcasted_iota(jnp.int32, (tp, k), 1)

    # the node walk is a fori_loop, not a python unroll: at the north-star
    # shape (20 chunks x k extract-max passes x R dims) unrolling blew the
    # TPU compile up beyond usability
    def chunk_body(ci, carry):
        run_val, run_idx = carry
        c0 = ci * n_chunk
        cols = pl.ds(c0, n_chunk)
        nvalid = nvalid_ref[0, cols] > 0                  # (NC,)

        la_num = jnp.zeros((tp, n_chunk), jnp.int32)
        dominant = jnp.full((tp, n_chunk), MAX_NODE_SCORE, jnp.int32)
        fp_num = jnp.zeros((tp, n_chunk), jnp.int32)
        n_diff = jnp.zeros((tp, n_chunk), jnp.int32)
        n_inter = jnp.zeros((tp, n_chunk), jnp.int32)
        fits = jnp.ones((tp, n_chunk), bool)
        inst_exceeded = jnp.zeros((tp, n_chunk), bool)
        agg_exceeded = jnp.zeros((tp, n_chunk), bool)

        for r in range(r_dims):
            alloc = alloc_ref[r, cols][None, :]           # (1, NC)
            reqd = reqd_ref[r, cols][None, :]
            usage = usage_ref[r, cols][None, :]
            agg = agg_ref[r, cols][None, :]
            podreq = podreq_ref[r, :][:, None]            # (TP, 1)
            podest = podest_ref[r, :][:, None]
            alloc_pos = alloc > 0

            # -- loadaware (load_aware.go:347) ---------------------------
            used = usage + podest                         # (TP, NC)
            ls_ok = alloc_pos & (used <= alloc)
            ls = jnp.where(
                ls_ok,
                _floordiv((alloc - used) * MAX_NODE_SCORE, alloc, alloc_pos),
                0)
            la_num = la_num + ls * la_w_ref[0, r]
            configured = la_w_ref[0, r] > 0
            dominant = jnp.where(
                configured, jnp.minimum(dominant, ls), dominant)

            # -- fitplus (node_resource_fit_plus_utils.go:58) ------------
            combined = reqd + podreq
            least = jnp.where(
                alloc_pos & (combined <= alloc),
                _floordiv((alloc - combined) * MAX_NODE_SCORE, alloc,
                          alloc_pos),
                0)
            most = _floordiv(jnp.minimum(combined, alloc) * MAX_NODE_SCORE,
                             alloc, alloc_pos)
            per_res = jnp.where(fp_most_ref[0, r] > 0, most, least)
            w_eff = jnp.where(podreq > 0, fp_w_ref[0, r], 0)   # (TP, 1)
            fp_num = fp_num + per_res * w_eff

            # -- scarce (scarce_resource_avoidance.go:89) ----------------
            diff = alloc_pos & (podreq == 0)
            n_diff = n_diff + diff
            n_inter = n_inter + (diff & (scarce_ref[0, r] > 0))

            # -- fit filter ----------------------------------------------
            free = jnp.where(nvalid[None, :], alloc - reqd, 0)
            fits = fits & ((podreq <= free) | (podreq == 0))

            # -- usage thresholds (load_aware.go:326 round-half-up) ------
            a_inst = MAX_SCALE * used + alloc // 2
            inst_exceeded = inst_exceeded | (
                (thr_ref[0, r] > 0) & alloc_pos
                & (a_inst >= (thr_ref[0, r] + 1) * alloc))
            a_agg = MAX_SCALE * (agg + podest) + alloc // 2
            agg_exceeded = agg_exceeded | (
                (agg_thr_ref[0, r] > 0) & alloc_pos
                & (a_agg >= (agg_thr_ref[0, r] + 1) * alloc))

        la = _floordiv(la_num + dominant * dom_w, la_den, la_den > 0)
        fp = jnp.where(
            fp_den[:, None] > 0,
            _floordiv(fp_num, fp_den[:, None], fp_den[:, None] > 0),
            MAX_NODE_SCORE)
        sc = jnp.where(
            (n_diff == 0) | (n_inter == 0),
            MAX_NODE_SCORE,
            _floordiv((n_diff - n_inter) * MAX_NODE_SCORE, n_diff,
                      n_diff > 0))
        scores = la * la_pw + fp * fp_pw + sc * sc_pw

        # selector-class feasibility: sel (TP, C) x one-hot(class) (C, NC)
        cls = nclass_ref[0, cols]                         # (NC,)
        in_range = cls < c_cap
        cls_safe = jnp.minimum(cls, c_cap - 1)
        onehot = (jax.lax.broadcasted_iota(jnp.int32, (c_cap, n_chunk), 0)
                  == cls_safe[None, :]).astype(jnp.float32)
        sel_ok = (jax.lax.dot_general(
            sel, onehot, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) > 0.5)    # (TP, NC)
        sel_ok = sel_ok & in_range[None, :]

        thr_ok = jnp.where(agg_enabled, ~agg_exceeded, ~inst_exceeded)
        feasible = (fits & thr_ok & sel_ok & nvalid[None, :]
                    & pod_valid[:, None])

        # ranked key (_ranked_scores): score high bits | rotated tie-break
        node_idx = c0 + jax.lax.broadcasted_iota(
            jnp.int32, (tp, n_chunk), 1)                  # (TP, NC)
        tb = (n - 1) - ((node_idx - rot) % n)
        q = jnp.clip(scores, 0, _SCORE_CLIP) >> spread_bits
        key = (q << _TB_BITS) | tb
        key = jnp.where(feasible, key, -1)

        # fold the chunk into the running top-k: k extract-max passes over
        # the (TP, K + NC) concat; ties resolve to the lowest node index,
        # matching lax.top_k
        cat_val = jnp.concatenate([run_val, key], axis=1)
        cat_idx = jnp.concatenate([run_idx, node_idx], axis=1)
        new_val = []
        new_idx = []
        for _ in range(k):
            m = jnp.max(cat_val, axis=1)                  # (TP,)
            is_m = cat_val == m[:, None]
            # lowest node index among maxima (for -1 sentinels index is
            # irrelevant)
            pick_idx = jnp.min(
                jnp.where(is_m, cat_idx, 1 << 30), axis=1)
            new_val.append(m)
            new_idx.append(pick_idx)   # may be a negative sentinel
            taken = is_m & (cat_idx == pick_idx[:, None])
            cat_val = jnp.where(taken, -2, cat_val)
        return jnp.stack(new_val, axis=1), jnp.stack(new_idx, axis=1)

    run_val, run_idx = jax.lax.fori_loop(
        0, n // n_chunk, chunk_body, (run_val, run_idx))
    out_val_ref[:, :] = run_val
    out_idx_ref[:, :] = jnp.where(run_val < 0, 0, run_idx)


def fused_score_topk(
    state: ClusterState,
    pods: PodBatch,
    cfg: ScoringConfig,
    k: int = 32,
    tile_pods: int = 128,
    n_chunk: int = 512,
    interpret: bool = False,
    spread_bits: int = 0,
):
    """(cand_key, cand_node) — bit-exact equivalent of
    ``lax.top_k(_ranked_scores(*score_pods(state, pods, cfg)), k)`` without
    the (P, N) HBM round-trips.  Factored (selector_mask) batches only."""
    from koordinator_tpu.ops import scoring

    if pods.selector_mask is None:
        raise ValueError("fused_score_topk needs a factored batch "
                         "(selector_mask); dense/hinted batches use the "
                         "XLA path")
    p = pods.capacity
    n = state.capacity
    r = pods.requests.shape[1]
    tp = min(tile_pods, p)
    nc = min(n_chunk, n)
    if n % nc:
        raise ValueError(f"node capacity {n} must tile by {nc}")
    # pad the pod axis up to a tile multiple: padded rows are invalid
    # (pod_valid=0 => key -1 everywhere) and sliced off the outputs
    p_pad = -(-p // tp) * tp
    pod_req = pods.requests
    pod_valid = pods.valid
    sel_mask = pods.selector_mask
    pod_est = scoring.estimate_pod_usage_by_band(
        pods.requests, cfg.estimator_factors, cfg.estimator_defaults)
    if p_pad != p:
        pad = ((0, p_pad - p), (0, 0))
        pod_req = jnp.pad(pod_req, pad)
        pod_est = jnp.pad(pod_est, pad)
        sel_mask = jnp.pad(sel_mask, pad)
        pod_valid = jnp.pad(pod_valid, ((0, p_pad - p),))

    scalars = jnp.stack([
        jnp.asarray(cfg.loadaware_dominant_weight, jnp.int32),
        jnp.asarray(cfg.loadaware_plugin_weight, jnp.int32),
        jnp.asarray(cfg.fitplus_plugin_weight, jnp.int32),
        jnp.asarray(cfg.scarce_plugin_weight, jnp.int32),
    ])[None, :]

    grid = (p_pad // tp,)
    pod_spec = pl.BlockSpec((r, tp), lambda i: (0, i),
                            memory_space=pltpu.VMEM)
    row_spec = pl.BlockSpec((1, tp), lambda i: (0, i),
                            memory_space=pltpu.VMEM)
    sel_spec = pl.BlockSpec((tp, sel_mask.shape[1]),
                            lambda i: (i, 0), memory_space=pltpu.VMEM)
    full = lambda shape: pl.BlockSpec(shape, lambda i: (0, 0),
                                      memory_space=pltpu.VMEM)

    kernel = functools.partial(
        _score_topk_kernel, n_chunk=nc, k=k, r_dims=r,
        spread_bits=spread_bits)
    out_val, out_idx = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pod_spec, pod_spec, row_spec, sel_spec,
            full((r, n)), full((r, n)), full((r, n)), full((r, n)),
            full((1, n)), full((1, n)),
            full((1, r)), full((1, r)), full((1, r)), full((1, r)),
            full((1, r)), full((1, r)), full((1, 4)),
        ],
        out_specs=[
            pl.BlockSpec((tp, k), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tp, k), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((p_pad, k), jnp.int32),
            jax.ShapeDtypeStruct((p_pad, k), jnp.int32),
        ],
        interpret=interpret,
    )(
        pod_req.T, pod_est.T, pod_valid[None, :].astype(jnp.int32),
        sel_mask.astype(jnp.int32),
        state.node_allocatable.T, state.node_requested.T,
        state.node_usage.T, state.node_agg_usage.T,
        state.node_valid[None, :].astype(jnp.int32),
        state.node_class[None, :],
        cfg.loadaware_resource_weights[None, :],
        cfg.fitplus_resource_weights[None, :],
        cfg.fitplus_most_allocated[None, :].astype(jnp.int32),
        cfg.scarce_dims[None, :].astype(jnp.int32),
        cfg.usage_thresholds[None, :],
        cfg.agg_usage_thresholds[None, :],
        scalars,
    )
    return out_val[:p], out_idx[:p]
