"""Fused Filter+Score+top-k as a Pallas TPU kernel.

``batch_assign``'s XLA candidate stage runs three passes: ``score_pods``
(which materializes the (P, N) int32 score tensor to HBM — 2 GB at the
north-star shape), ``_ranked_scores`` (another (P, N)), and the top-k
reduction (a third full-width read).  This kernel streams instead: the grid
is (pod tiles × node chunks); each step scores one (TP, NC) tile, computes
the ranked key in registers, and folds it into a per-pod **bucket array**
of running maxima — the (P, N) intermediates never touch HBM, only the
(P, L) bucket winners do (L = ``n_bucket``, 2048 by default at scale vs
N = 10240).  The final per-pod top-k over the small (P, L) output runs in
plain XLA outside the kernel.

Bucketing: chunk column c of chunk j folds into bucket (j*NC + c) mod L,
i.e. node n lands in bucket n mod L.  Per-pod ranking keys are UNIQUE
(the rotated tie-break is a permutation of node indices), so:

- when L >= N every node owns its bucket and the result is bit-exact with
  ``lax.top_k(_ranked_scores(*score_pods(...)), k)`` — asserted in
  tests/test_pallas_score.py via interpret mode;
- when L < N two nodes L apart can collide and candidate RECALL becomes
  approximate — but the rotated tie-break ranks a pod's equal-scored
  candidates by *consecutive* node index, and consecutive indices occupy
  distinct buckets, so the spread that matters for the solve survives.
  Acceptance downstream enforces fit and quota exactly either way (same
  contract as the ``approx_max_k`` path).

The fold itself is elementwise (2 selects per chunk), so the Mosaic body
stays tiny — the previous design's per-chunk k-pass extract-max unroll
(20 chunks x k passes at the north-star shape) made TPU compiles unusable.
Output blocks are revisited across the chunk axis of the grid (the Pallas
accumulator pattern); the first visit initializes the buckets to -1.

Layouts are transposed (R leading) so pods/nodes ride the 128-lane axis;
R (=10) unrolls as python loops.  The selector-class feasibility gather
``selector_mask[:, node_class]`` becomes a one-hot matmul on the MXU.

Reference parity anchors are the same as ops/scoring.py (load_aware.go:347,
node_resource_fit_plus_utils.go:58, scarce_resource_avoidance.go:89,
load_aware.go:326 thresholds).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from koordinator_tpu.ops.assignment import ScoringConfig
from koordinator_tpu.ops.batch_assign import (
    _SCORE_CLIP,
    _TB_BITS,
    check_node_capacity,
)
from koordinator_tpu.ops.scoring import MAX_NODE_SCORE, exact_floordiv
from koordinator_tpu.state.cluster_state import ClusterState, PodBatch

from koordinator_tpu.ops.filtering import MAX_SCALE

def _floordiv(num, den, den_pos):
    """exact_floordiv guarded for den<=0 rows (returns 0 there)."""
    safe = jnp.maximum(den, 1)
    return jnp.where(den_pos, exact_floordiv(jnp.maximum(num, 0), safe), 0)


def _score_bucket_kernel(
    # pod tile refs (blocked over P)
    podreq_ref,      # (R, TP) int32
    podest_ref,      # (R, TP) int32
    podvalid_ref,    # (1, TP) int32
    sel_ref,         # (TP, C) int32 0/1
    # node chunk refs — the node axis is viewed as (S, L) with
    # n = s*L + l (bucket l = n mod L), blocked (.., 1, NC) at (s, b)
    alloc_ref,       # (R, 1, NC) int32
    reqd_ref,        # (R, 1, NC) int32
    usage_ref,       # (R, 1, NC) int32
    agg_ref,         # (R, 1, NC) int32
    nvalid_ref,      # (1, 1, NC) int32
    nclass_ref,      # (1, 1, NC) int32
    # cfg refs
    la_w_ref,        # (1, R) int32 loadaware weights
    fp_w_ref,        # (1, R) int32 fitplus weights
    fp_most_ref,     # (1, R) int32 bool
    scarce_ref,      # (1, R) int32 bool
    thr_ref,         # (1, R) int32 usage thresholds
    agg_thr_ref,     # (1, R) int32 aggregated thresholds
    scalars_ref,     # (1, 4) int32: [dominant_w, la_plugin_w, fp_plugin_w,
                     #               scarce_plugin_w]
    # outputs — bucket accumulators; the s grid axis is innermost, so all
    # revisits of one output block are consecutive (Pallas accumulation).
    # Stratum 0 owns (val, idx); every further stratum owns
    # (sel, ord, idx): selected by its own key, carrying the stratum-0
    # ORDER key of the winning node so the rounds rank all candidates on
    # one scale (*out_refs order: val0, idx0, sel1, ord1, idx1, ...)
    *out_refs,
    n_chunk: int,
    r_dims: int,
    spread_bits: tuple,
):
    tp = podreq_ref.shape[1]
    tile = pl.program_id(0)
    b = pl.program_id(1)        # bucket block
    s = pl.program_id(2)        # sub-step within the bucket block
    l_total = pl.num_programs(1) * n_chunk
    n = pl.num_programs(2) * l_total
    c0 = s * l_total + b * n_chunk   # global index of this block's node 0

    dom_w = scalars_ref[0, 0]
    la_pw = scalars_ref[0, 1]
    fp_pw = scalars_ref[0, 2]
    sc_pw = scalars_ref[0, 3]
    agg_enabled = jnp.any(agg_thr_ref[0, :] > 0)

    pod_valid = podvalid_ref[0, :] > 0                    # (TP,)
    # fitplus per-pod weight sum over requested dims (den), (TP,)
    fp_den = jnp.zeros((tp,), jnp.int32)
    la_wsum = jnp.int32(0)
    for r in range(r_dims):
        fp_den = fp_den + jnp.where(podreq_ref[r, :] > 0, fp_w_ref[0, r], 0)
        la_wsum = la_wsum + la_w_ref[0, r]
    la_den = la_wsum + dom_w                              # scalar
    sel = sel_ref[:, :].astype(jnp.float32)               # (TP, C)
    c_cap = sel.shape[1]

    # rotated tie-break offsets for this tile's global pod rows
    pod_ids = tile * tp + jax.lax.broadcasted_iota(jnp.int32, (tp, 1), 0)
    rot = pod_ids * 7919                                  # (TP, 1)

    nvalid = nvalid_ref[0, 0, :] > 0                      # (NC,)

    la_num = jnp.zeros((tp, n_chunk), jnp.int32)
    dominant = jnp.full((tp, n_chunk), MAX_NODE_SCORE, jnp.int32)
    fp_num = jnp.zeros((tp, n_chunk), jnp.int32)
    n_diff = jnp.zeros((tp, n_chunk), jnp.int32)
    n_inter = jnp.zeros((tp, n_chunk), jnp.int32)
    fits = jnp.ones((tp, n_chunk), bool)
    inst_exceeded = jnp.zeros((tp, n_chunk), bool)
    agg_exceeded = jnp.zeros((tp, n_chunk), bool)

    for r in range(r_dims):
        alloc = alloc_ref[r, 0, :][None, :]               # (1, NC)
        reqd = reqd_ref[r, 0, :][None, :]
        usage = usage_ref[r, 0, :][None, :]
        agg = agg_ref[r, 0, :][None, :]
        podreq = podreq_ref[r, :][:, None]                # (TP, 1)
        podest = podest_ref[r, :][:, None]
        alloc_pos = alloc > 0

        # -- loadaware (load_aware.go:347) ---------------------------
        used = usage + podest                             # (TP, NC)
        ls_ok = alloc_pos & (used <= alloc)
        ls = jnp.where(
            ls_ok,
            _floordiv((alloc - used) * MAX_NODE_SCORE, alloc, alloc_pos),
            0)
        la_num = la_num + ls * la_w_ref[0, r]
        configured = la_w_ref[0, r] > 0
        dominant = jnp.where(
            configured, jnp.minimum(dominant, ls), dominant)

        # -- fitplus (node_resource_fit_plus_utils.go:58) ------------
        combined = reqd + podreq
        least = jnp.where(
            alloc_pos & (combined <= alloc),
            _floordiv((alloc - combined) * MAX_NODE_SCORE, alloc,
                      alloc_pos),
            0)
        most = _floordiv(jnp.minimum(combined, alloc) * MAX_NODE_SCORE,
                         alloc, alloc_pos)
        per_res = jnp.where(fp_most_ref[0, r] > 0, most, least)
        w_eff = jnp.where(podreq > 0, fp_w_ref[0, r], 0)   # (TP, 1)
        fp_num = fp_num + per_res * w_eff

        # -- scarce (scarce_resource_avoidance.go:89) ----------------
        diff = alloc_pos & (podreq == 0)
        n_diff = n_diff + diff
        n_inter = n_inter + (diff & (scarce_ref[0, r] > 0))

        # -- fit filter ----------------------------------------------
        free = jnp.where(nvalid[None, :], alloc - reqd, 0)
        fits = fits & ((podreq <= free) | (podreq == 0))

        # -- usage thresholds (load_aware.go:326 round-half-up) ------
        a_inst = MAX_SCALE * used + alloc // 2
        inst_exceeded = inst_exceeded | (
            (thr_ref[0, r] > 0) & alloc_pos
            & (a_inst >= (thr_ref[0, r] + 1) * alloc))
        a_agg = MAX_SCALE * (agg + podest) + alloc // 2
        agg_exceeded = agg_exceeded | (
            (agg_thr_ref[0, r] > 0) & alloc_pos
            & (a_agg >= (agg_thr_ref[0, r] + 1) * alloc))

    la = _floordiv(la_num + dominant * dom_w, la_den, la_den > 0)
    fp = jnp.where(
        fp_den[:, None] > 0,
        _floordiv(fp_num, fp_den[:, None], fp_den[:, None] > 0),
        MAX_NODE_SCORE)
    sc = jnp.where(
        (n_diff == 0) | (n_inter == 0),
        MAX_NODE_SCORE,
        _floordiv((n_diff - n_inter) * MAX_NODE_SCORE, n_diff,
                  n_diff > 0))
    scores = la * la_pw + fp * fp_pw + sc * sc_pw

    # selector-class feasibility: sel (TP, C) x one-hot(class) (C, NC)
    cls = nclass_ref[0, 0, :]                             # (NC,)
    in_range = cls < c_cap
    cls_safe = jnp.minimum(cls, c_cap - 1)
    onehot = (jax.lax.broadcasted_iota(jnp.int32, (c_cap, n_chunk), 0)
              == cls_safe[None, :]).astype(jnp.float32)
    sel_ok = (jax.lax.dot_general(
        sel, onehot, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) > 0.5)        # (TP, NC)
    sel_ok = sel_ok & in_range[None, :]

    thr_ok = jnp.where(agg_enabled, ~agg_exceeded, ~inst_exceeded)
    feasible = (fits & thr_ok & sel_ok & nvalid[None, :]
                & pod_valid[:, None])

    # ranked keys (_ranked_scores), one per stratum: score high bits |
    # rotated tie-break; scores are computed once above
    node_idx = c0 + jax.lax.broadcasted_iota(
        jnp.int32, (tp, n_chunk), 1)                      # (TP, NC)
    tb = (n - 1) - ((node_idx - rot) % n)
    clipped = jnp.clip(scores, 0, _SCORE_CLIP)
    keys = []
    for sb in spread_bits:
        key = ((clipped >> sb) << _TB_BITS) | tb
        keys.append(jnp.where(feasible, key, -1))

    # bucket fold: strictly-greater keeps the earlier (lower-index) node —
    # keys are unique per pod, so ties never actually occur and the result
    # is bit-exact with lax.top_k whenever L >= N.  s == 0 is the first
    # visit to this output block and initializes the accumulator.
    first = s == 0
    cur_val = jnp.where(first, -1, out_refs[0][:, :])
    cur_idx = jnp.where(first, 0, out_refs[1][:, :])
    taken = keys[0] > cur_val
    out_refs[0][:, :] = jnp.maximum(keys[0], cur_val)
    out_refs[1][:, :] = jnp.where(taken, node_idx, cur_idx)
    for i, key in enumerate(keys[1:]):
        # strat_* names: do NOT shadow the sel_ref selector-mask input
        strat_sel, strat_ord, strat_idx = out_refs[2 + 3 * i: 5 + 3 * i]
        cur_sel = jnp.where(first, -1, strat_sel[:, :])
        cur_ord = jnp.where(first, -1, strat_ord[:, :])
        cur_idx = jnp.where(first, 0, strat_idx[:, :])
        taken = key > cur_sel
        strat_sel[:, :] = jnp.maximum(key, cur_sel)
        strat_ord[:, :] = jnp.where(taken, keys[0], cur_ord)
        strat_idx[:, :] = jnp.where(taken, node_idx, cur_idx)


def fused_score_topk(
    state: ClusterState,
    pods: PodBatch,
    cfg: ScoringConfig,
    k: int = 32,
    tile_pods: int = 128,
    n_chunk: int = 512,
    n_bucket: int | None = None,
    interpret: bool = False,
    spread_bits=0,
):
    """(cand_key, cand_node) — streaming equivalent of
    ``lax.top_k(_ranked_scores(*score_pods(state, pods, cfg)), k)`` without
    the (P, N) HBM round-trips.  Factored (selector_mask) batches only.

    ``n_bucket`` (L) sizes the per-pod bucket accumulator: bit-exact when
    L >= N, approximate-recall when L < N (see module docstring).  The
    default clamps ``4 * n_chunk`` to [k-coverage, N] — exact for every
    test-sized problem, 2048 buckets at the 10,240-node north star.

    ``spread_bits`` may be a tuple of quantization depths — stratified
    selection matching select_candidates: k splits across the strata,
    each stratum folds by its own key, and all returned cand_key values
    are on the FIRST stratum's scale.
    """
    from koordinator_tpu.ops import scoring

    if pods.selector_mask is None:
        raise ValueError("fused_score_topk needs a factored batch "
                         "(selector_mask); dense/hinted batches use the "
                         "XLA path")
    p = pods.capacity
    n = state.capacity
    check_node_capacity(n)
    r = pods.requests.shape[1]
    tp = min(tile_pods, p)
    nc = min(n_chunk, n)
    if n % nc:
        raise ValueError(f"node capacity {n} must tile by {nc}")
    if n_bucket is None:
        n_bucket = 4 * nc
    # L must cover k, tile by the chunk width, and divide N (the node axis
    # is viewed as (N//L, L)).  Take the smallest chunk-multiple divisor of
    # N at or above the request — worst case L = N, which is the exact case.
    m = n // nc
    d_target = max(1, min(m, -(-max(n_bucket, k) // nc)))
    d = next(dd for dd in range(d_target, m + 1) if m % dd == 0)
    n_bucket = d * nc
    k = min(k, n_bucket)

    # pad the pod axis up to a tile multiple: padded rows are invalid
    # (pod_valid=0 => key -1 everywhere) and sliced off the outputs
    p_pad = -(-p // tp) * tp
    pod_req = pods.requests
    pod_valid = pods.valid
    sel_mask = pods.selector_mask
    pod_est = scoring.estimate_pod_usage_by_band(
        pods.requests, cfg.estimator_factors, cfg.estimator_defaults)
    if p_pad != p:
        pad = ((0, p_pad - p), (0, 0))
        pod_req = jnp.pad(pod_req, pad)
        pod_est = jnp.pad(pod_est, pad)
        sel_mask = jnp.pad(sel_mask, pad)
        pod_valid = jnp.pad(pod_valid, ((0, p_pad - p),))

    scalars = jnp.stack([
        jnp.asarray(cfg.loadaware_dominant_weight, jnp.int32),
        jnp.asarray(cfg.loadaware_plugin_weight, jnp.int32),
        jnp.asarray(cfg.fitplus_plugin_weight, jnp.int32),
        jnp.asarray(cfg.scarce_plugin_weight, jnp.int32),
    ])[None, :]

    # the node axis is viewed as (S, L): n = s*L + l, bucket = n mod L.
    # Grid order (tile, bucket-block, s) keeps all revisits of one output
    # block consecutive — required for Pallas output accumulation on TPU.
    n_sub = n // n_bucket
    grid = (p_pad // tp, n_bucket // nc, n_sub)
    pod_spec = pl.BlockSpec((r, tp), lambda i, b, s: (0, i),
                            memory_space=pltpu.VMEM)
    row_spec = pl.BlockSpec((1, tp), lambda i, b, s: (0, i),
                            memory_space=pltpu.VMEM)
    sel_spec = pl.BlockSpec((tp, sel_mask.shape[1]),
                            lambda i, b, s: (i, 0),
                            memory_space=pltpu.VMEM)
    node_spec = pl.BlockSpec((r, 1, nc), lambda i, b, s: (0, s, b),
                             memory_space=pltpu.VMEM)
    nrow_spec = pl.BlockSpec((1, 1, nc), lambda i, b, s: (0, s, b),
                             memory_space=pltpu.VMEM)
    cfg_spec = lambda shape: pl.BlockSpec(shape, lambda i, b, s: (0, 0),
                                          memory_space=pltpu.VMEM)
    out_spec = pl.BlockSpec((tp, nc), lambda i, b, s: (i, b),
                            memory_space=pltpu.VMEM)

    node3 = lambda a: a.T.reshape(r, n_sub, n_bucket)
    nrow3 = lambda a: a.reshape(1, n_sub, n_bucket)

    strata = tuple(spread_bits) if isinstance(
        spread_bits, (tuple, list)) else (spread_bits,)
    # stratum 0: (val, idx); each further stratum: (sel, ord, idx)
    n_outs = 2 + 3 * (len(strata) - 1)
    kernel = functools.partial(
        _score_bucket_kernel, n_chunk=nc, r_dims=r,
        spread_bits=strata)
    outs = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pod_spec, pod_spec, row_spec, sel_spec,
            node_spec, node_spec, node_spec, node_spec,
            nrow_spec, nrow_spec,
            cfg_spec((1, r)), cfg_spec((1, r)), cfg_spec((1, r)),
            cfg_spec((1, r)), cfg_spec((1, r)), cfg_spec((1, r)),
            cfg_spec((1, 4)),
        ],
        out_specs=[out_spec] * n_outs,
        out_shape=[jax.ShapeDtypeStruct((p_pad, n_bucket), jnp.int32)
                   ] * n_outs,
        interpret=interpret,
    )(
        pod_req.T, pod_est.T, pod_valid[None, :].astype(jnp.int32),
        sel_mask.astype(jnp.int32),
        node3(state.node_allocatable), node3(state.node_requested),
        node3(state.node_usage), node3(state.node_agg_usage),
        nrow3(state.node_valid.astype(jnp.int32)),
        nrow3(state.node_class),
        cfg.loadaware_resource_weights[None, :],
        cfg.fitplus_resource_weights[None, :],
        cfg.fitplus_most_allocated[None, :].astype(jnp.int32),
        cfg.scarce_dims[None, :].astype(jnp.int32),
        cfg.usage_thresholds[None, :],
        cfg.agg_usage_thresholds[None, :],
        scalars,
    )
    # final per-pod top-k over the small (P, L) bucket arrays in plain XLA.
    # Bucket maxima carry unique keys (or -1), and bucket order under
    # lax.top_k ties only matters for -1 fills, whose idx is sanitized to 0.
    from koordinator_tpu.ops.batch_assign import _stratum_splits

    splits = _stratum_splits(k, len(strata))
    keys_out, nodes_out = [], []
    # stratum 0: val doubles as both selection and order key
    ck, pos = jax.lax.top_k(outs[0][:p], splits[0])
    cn = jnp.take_along_axis(outs[1][:p], pos, axis=1)
    keys_out.append(ck)
    nodes_out.append(jnp.where(ck < 0, 0, cn))
    for i, k_i in enumerate(splits[1:]):
        if k_i == 0:
            continue
        sel, ordk, idx = outs[2 + 3 * i: 5 + 3 * i]
        sv, pos = jax.lax.top_k(sel[:p], k_i)
        ck = jnp.take_along_axis(ordk[:p], pos, axis=1)
        ck = jnp.where(sv < 0, -1, ck)
        cn = jnp.take_along_axis(idx[:p], pos, axis=1)
        keys_out.append(ck)
        nodes_out.append(jnp.where(ck < 0, 0, cn))
    if len(keys_out) == 1:
        return keys_out[0], nodes_out[0]
    return (jnp.concatenate(keys_out, axis=1),
            jnp.concatenate(nodes_out, axis=1))
