"""Quota overuse revoke: evict pods of quotas whose used exceeds runtime.

Reference: ``pkg/scheduler/plugins/elasticquota/quota_overuse_revoke.go`` —
a per-quota monitor flags quotas whose used has exceeded runtime continuously
for ``delay_evict_sec`` (the runtime shrinks when other quotas' requests rise,
so previously-admitted pods can overshoot); victim selection then walks the
quota's pods least-important-first, removing until used <= runtime, and
finally tries to assign back most-important-first (getToRevokePodList).

TPU redesign: both walks become ONE pair of segmented ``lax.scan`` passes over
the globally-sorted bound-pod list, so every over-used quota's victim set is
solved in the same kernel call — the per-quota Go loops are the batch axis
here.  The host controller keeps only the timers.

Divergence note: the reference compares used vs runtime on every resource
name present; we compare on the quota's declared-max (checked) dims, matching
the admission convention in :mod:`koordinator_tpu.quota.admission` (an
undeclared dim has no meaningful runtime).
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from koordinator_tpu.ops.preemption import ScheduledPods


def select_overuse_victims(
    sched: ScheduledPods,
    used: jnp.ndarray,      # (Q, R) int32 per-quota used
    runtime: jnp.ndarray,   # (Q, R) int32 per-quota runtime
    checked: jnp.ndarray,   # (Q, R) bool — dims declared in the quota's max
    pdb_allowed: jnp.ndarray | None = None,   # (P,) int32 budgets
) -> jnp.ndarray:
    """(V,) bool revoke mask across every quota at once.

    Phase 1 (ascending importance): while the pod's quota is still over on
    any checked dim, tentatively remove the pod.  Phase 2 (descending
    importance): reprieve tentative victims that fit back under runtime —
    unless the quota is over even with everything removed, in which case all
    tentative victims go (the reference's "should evict all" branch).
    """
    cand = sched.valid & ~sched.non_preemptible & (sched.quota_id >= 0)
    blocked = jnp.zeros(sched.capacity, bool)
    if pdb_allowed is not None:
        # exhausted disruption budgets exclude pods INSIDE the selection,
        # so a protected lowest-priority pod doesn't permanently block
        # revocation when an evictable alternative exists (note: per-PDB
        # budgets gate counts at commit; the kernel only masks zero-budget
        # pods, matching the preemption kernel's candidate masking)
        blocked = cand & (sched.pdb_id >= 0) & (
            pdb_allowed[jnp.maximum(sched.pdb_id, 0)] <= 0)
        cand = cand & ~blocked
    qid = jnp.maximum(sched.quota_id, 0)
    # ascending importance: lowest priority first, stable by row index
    pri_key = jnp.where(cand, sched.priority, jnp.int32(2**31 - 1))
    asc = jnp.lexsort((jnp.arange(sched.capacity), pri_key))

    def phase1(u, j):
        q = qid[j]
        over = jnp.any((u[q] > runtime[q]) & checked[q])
        do = cand[j] & over
        u = u.at[q].add(jnp.where(do, -sched.requests[j], 0))
        return u, do

    u1, tent_asc = jax.lax.scan(phase1, used, asc)
    tentative = jnp.zeros(sched.capacity, bool).at[asc].set(tent_asc)

    # quotas over even after removing every candidate ("hopeless"): with
    # nothing PDB-blocked, every candidate goes (the reference's
    # should-evict-all branch — the overshoot is from non-preemptible
    # usage); with a blocked pod in the quota, eviction provably cannot
    # cure the overuse, so SKIP the quota this cycle (it re-arms and
    # retries once disruption budgets recover) instead of dumping pods
    # to no effect
    hopeless = jnp.any((u1 > runtime) & checked, axis=-1)  # (Q,)
    q_cap = used.shape[0]
    has_blocked = (jnp.zeros(q_cap, bool)
                   .at[jnp.where(blocked, qid, q_cap)].set(
                       True, mode="drop"))
    skip_quota = hopeless & has_blocked

    def phase2(u, j):
        q = qid[j]
        req = sched.requests[j]
        # reprieve fit only consults CHECKED dims (phase1 and the hopeless
        # test do the same): an undeclared dim has no meaningful runtime
        # and must not veto a reprieve
        fits = jnp.all((u[q] + req <= runtime[q]) | (req == 0)
                       | ~checked[q])
        # hopeless quotas with nothing blocked keep the reference's
        # should-evict-all: a pod requesting zero on the overshoot dim
        # could otherwise "fit back" and dodge the branch
        back = tentative[j] & ((fits & ~hopeless[q]) | skip_quota[q])
        u = u.at[q].add(jnp.where(back, req, 0))
        return u, tentative[j] & ~back

    desc = asc[::-1]
    _, revoke_desc = jax.lax.scan(phase2, u1, desc)
    return jnp.zeros(sched.capacity, bool).at[desc].set(revoke_desc)


class QuotaOveruseRevokeController:
    """Host loop: timers + eviction callback around the batched kernel.

    ``scheduler`` supplies the bound-pod registry and quota tree; victims are
    evicted via ``revoke_fn(pod_name, quota_name)`` and released through the
    scheduler's own accounting (remove_bound_pod + quota used).
    """

    def __init__(
        self,
        scheduler,
        revoke_fn,
        delay_evict_sec: float = 5.0,
        clock=time.monotonic,
    ):
        if revoke_fn is None:
            # mirroring the preemption guard: releasing a victim's
            # accounting without anyone actually evicting it would
            # oversubscribe its node against a still-running pod
            raise ValueError("overuse revoke needs a revoke_fn that "
                             "performs the eviction")
        self.scheduler = scheduler
        self.revoke_fn = revoke_fn
        self.delay_evict_sec = delay_evict_sec
        self.clock = clock
        self._last_under: dict[str, float] = {}
        self._kernel = jax.jit(select_overuse_victims)

    def _over_used(self, qnode) -> bool:
        from koordinator_tpu.quota.tree import UNBOUNDED

        checked = qnode.max != UNBOUNDED
        return bool(np.any((qnode.used > qnode.runtime) & checked))

    def monitor(self) -> list[str]:
        """Quotas over-used continuously past the delay (monitor())."""
        tree = self.scheduler.quota_tree
        if tree is None:
            return []
        now = self.clock()
        triggered = []
        for name, qnode in tree.nodes.items():
            if self._over_used(qnode):
                since = self._last_under.setdefault(name, now)
                if now - since > self.delay_evict_sec:
                    triggered.append(name)
                    self._last_under[name] = now  # re-arm after trigger
            else:
                self._last_under[name] = now
        return triggered

    def revoke_once(self) -> list[str]:
        """One controller cycle: returns the evicted pod names."""
        triggered = set(self.monitor())
        if not triggered:
            return []
        tree = self.scheduler.quota_tree
        quota_index = {n: i for i, n in enumerate(sorted(tree.nodes))}
        sched, bound_names = self.scheduler._build_scheduled(quota_index)
        if not bound_names:
            return []

        from koordinator_tpu.quota.admission import HEADROOM_CLAMP
        from koordinator_tpu.quota.tree import UNBOUNDED

        q = len(quota_index)
        used = np.zeros((max(q, 1), sched.requests.shape[1]), np.int32)
        runtime = np.zeros_like(used)
        checked = np.zeros(used.shape, bool)
        for name, i in quota_index.items():
            qnode = tree.nodes[name]
            used[i] = np.clip(qnode.used, 0, HEADROOM_CLAMP)
            runtime[i] = np.clip(qnode.runtime, 0, HEADROOM_CLAMP)
            # only triggered quotas participate; others are "never over"
            if name in triggered:
                checked[i] = qnode.max != UNBOUNDED

        _, pdb_allowed = self.scheduler._pdb_arrays()
        revoke = np.asarray(self._kernel(
            sched, jnp.asarray(used), jnp.asarray(runtime),
            jnp.asarray(checked), jnp.asarray(pdb_allowed),
        ))
        evicted = []
        for v in np.flatnonzero(revoke):
            name = bound_names[v]
            bp = self.scheduler.bound.get(name)
            if bp is None:
                continue
            # PDB budgets bind here as in the preemption path: a pod whose
            # disruption budget is exhausted survives (the quota stays
            # armed and retries once the budget recovers)
            matching_pdbs = [
                rec for rec in self.scheduler.pdbs.values()
                if rec.matches(bp.labels)
            ]
            if any(rec.allowed <= 0 for rec in matching_pdbs):
                continue
            for rec in matching_pdbs:
                rec.allowed -= 1
            quota = bp.quota
            self.scheduler.remove_bound_pod(name)
            self.scheduler._charge_quota_used(bp, sign=-1)
            self.revoke_fn(name, quota)
            evicted.append(name)
        return evicted
