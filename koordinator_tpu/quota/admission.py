"""Elastic-quota admission as a device kernel.

The reference's hot-path check (``elasticquota/plugin.go`` PreFilter:
used + podRequest <= runtime at the pod's quota, optionally recursively up the
parent chain — checkQuotaRecursive, plugin.go:256-304) becomes tensor algebra:

- the host flattens the quota tree into an ancestor-chain index matrix
  (Q, D) and headroom tensors, clamping int64 headroom into int32 (a clamped
  headroom only matters when it exceeds any possible pod request, so admission
  decisions are unchanged);
- :func:`quota_admission_mask` then answers a whole pod batch at once, and
  :func:`charge_quota` applies Reserve-time accounting to every ancestor so
  sequential assignment sees quota feedback on device.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS
from koordinator_tpu.quota.tree import UNBOUNDED, QuotaTree

#: int32 headroom clamp; far above any single pod request so clamping cannot
#: flip an admission decision, far below int32 max so Reserve-time subtraction
#: cannot underflow across a batch.
HEADROOM_CLAMP = 2**30


@struct.dataclass
class QuotaDeviceState:
    """Flattened quota tree on device. Q quota rows, D max chain depth."""

    headroom: jax.Array   # (Q, R) int32: runtime - used, clamped
    min_headroom: jax.Array  # (Q, R) int32: min - nonPreemptibleUsed, clamped
    checked: jax.Array    # (Q, R) bool: dims declared in the quota's max
    chain: jax.Array      # (Q, D) int32 ancestor indices (self first), -1 pad
    valid: jax.Array      # (Q,) bool

    @property
    def capacity(self) -> int:
        return self.headroom.shape[0]

    @classmethod
    def from_tree(
        cls, tree: QuotaTree, max_depth: int = 8, capacity: int | None = None
    ) -> tuple["QuotaDeviceState", dict[str, int]]:
        """Flatten; returns (state, name->row index map)."""
        names = sorted(tree.nodes)
        q = len(names)
        cap = capacity if capacity is not None else max(8, 1 << (q - 1).bit_length() if q else 3)
        if cap < q:
            raise ValueError(f"capacity {cap} < {q} quotas in tree")
        index = {n: i for i, n in enumerate(names)}

        headroom = np.zeros((cap, NUM_RESOURCE_DIMS), np.int32)
        min_headroom = np.zeros((cap, NUM_RESOURCE_DIMS), np.int32)
        checked = np.zeros((cap, NUM_RESOURCE_DIMS), bool)
        chain = np.full((cap, max_depth), -1, np.int32)
        valid = np.zeros(cap, bool)

        for name, i in index.items():
            node = tree.nodes[name]
            hr = node.runtime - node.used
            mh = node.min - node.non_preemptible_used
            headroom[i] = np.clip(hr, -HEADROOM_CLAMP, HEADROOM_CLAMP)
            min_headroom[i] = np.clip(mh, -HEADROOM_CLAMP, HEADROOM_CLAMP)
            checked[i] = node.max != UNBOUNDED
            anc = tree.ancestors(name)
            if len(anc) > max_depth:
                raise ValueError(f"quota chain deeper than {max_depth}: {anc}")
            chain[i, : len(anc)] = [index[a] for a in anc]
            valid[i] = True

        state = cls(
            headroom=jnp.asarray(headroom),
            min_headroom=jnp.asarray(min_headroom),
            checked=jnp.asarray(checked),
            chain=jnp.asarray(chain),
            valid=jnp.asarray(valid),
        )
        return state, index


def quota_admission_mask(
    quota: QuotaDeviceState,
    pod_requests: jnp.ndarray,     # (P, R) int32
    pod_quota_id: jnp.ndarray,     # (P,) int32, -1 = no quota (always admitted)
    non_preemptible: jnp.ndarray | None = None,  # (P,) bool
    check_parents: bool = True,
) -> jnp.ndarray:
    """(P,) bool: pod fits its quota chain's headroom on every checked dim.

    Parity: plugin.go PreFilter — podRequest masked to the quota's declared
    max dims, used+request <= runtime; non-preemptible pods additionally check
    nonPreemptibleUsed+request <= min; EnableCheckParentQuota walks ancestors.
    """
    qid = jnp.maximum(pod_quota_id, 0)
    chain = quota.chain[qid]                       # (P, D)
    depth = chain.shape[1] if check_parents else 1
    chain = chain[:, :depth]
    level_ok = chain >= 0                          # (P, D)
    safe = jnp.maximum(chain, 0)

    headroom = quota.headroom[safe]                # (P, D, R)
    # The reference masks the pod request ONCE by the pod's own quota's
    # declared max dims (quotav1.Mask in PreFilter) and checks those same dims
    # at every ancestor — an ancestor's own max never widens or narrows the
    # checked set.
    checked = quota.checked[qid][:, None, :]       # (P, 1, R)
    req = pod_requests[:, None, :]                 # (P, 1, R)
    fits = (req <= headroom) | ~checked | (req == 0)
    ok = jnp.all(jnp.all(fits, axis=-1) | ~level_ok, axis=-1)  # (P,)

    if non_preemptible is not None:
        own = quota.min_headroom[qid]              # (P, R)
        np_fits = jnp.all(
            (pod_requests <= own) | ~quota.checked[qid] | (pod_requests == 0),
            axis=-1,
        )
        ok = ok & (np_fits | ~non_preemptible)

    # A stale/padded quota row (valid False) must reject, not vacuously admit;
    # only quota_id < 0 ("no quota") bypasses the check entirely.
    ok = ok & quota.valid[qid]
    return ok | (pod_quota_id < 0)


def charge_quota_batch(
    quota: QuotaDeviceState,
    requests: jnp.ndarray,        # (P, R) int32
    quota_ids: jnp.ndarray,       # (P,) int32, -1 = no-op
    mask: jnp.ndarray,            # (P,) bool — which pods actually charge
    non_preemptible: jnp.ndarray, # (P,) bool
    sign: int = 1,
) -> QuotaDeviceState:
    """Reserve/Unreserve accounting for a pod batch in one scatter.

    Subtracts (sign=1) or returns (sign=-1) each masked pod's request from
    every ancestor's headroom; non-preemptible pods additionally consume their
    own quota's min headroom (the reference updates NonPreemptibleUsed
    alongside Used)."""
    qid = jnp.maximum(quota_ids, 0)
    chain = quota.chain[qid]                  # (P, D)
    active = (
        (chain >= 0)
        & (quota_ids >= 0)[:, None]
        & mask[:, None]
        & quota.valid[qid][:, None]
    )
    safe = jnp.maximum(chain, 0)              # (P, D)
    delta = jnp.where(
        active[:, :, None], -sign * requests[:, None, :], 0
    )  # (P, D, R)
    headroom = quota.headroom.at[safe.reshape(-1)].add(
        delta.reshape(-1, requests.shape[-1])
    )
    np_active = (
        mask & (quota_ids >= 0) & non_preemptible & quota.valid[qid]
    )
    min_delta = jnp.where(np_active[:, None], -sign * requests, 0)
    min_headroom = quota.min_headroom.at[qid].add(min_delta)
    return quota.replace(headroom=headroom, min_headroom=min_headroom)


def charge_quota(
    quota: QuotaDeviceState,
    request: jnp.ndarray,    # (R,) int32
    quota_id: jnp.ndarray,   # () int32, -1 = no-op
    sign: int = 1,
    non_preemptible: jnp.ndarray | bool = False,
) -> QuotaDeviceState:
    """Single-pod convenience wrapper over :func:`charge_quota_batch`."""
    return charge_quota_batch(
        quota,
        request[None, :],
        quota_id[None],
        jnp.ones((1,), bool),
        jnp.asarray(non_preemptible)[None],
        sign=sign,
    )
