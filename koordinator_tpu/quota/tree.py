"""The quota tree and its fair-share runtime calculation (host side, exact).

Semantics ported from the reference's
``pkg/scheduler/plugins/elasticquota/core/runtime_quota_calculator.go``:

- ``redistribution`` (:119): each child's runtime starts at
  autoScaleMin = max(min, guarantee) if it requests more than that, else at its
  request (or autoScaleMin when the group refuses to lend, allowLentResource
  false). The remaining parent resource is then water-filled over the
  still-hungry children proportionally to sharedWeight, iterating as children
  saturate at their request.
- ``computeHamiltonDeltas`` (:194): each round's pool splits by the largest-
  remainder (Hamilton) method — base_i = floor(w_i * pool / W), then +1 to the
  largest remainders (ties by quota name ascending) until the residual is gone,
  so every round conserves the pool exactly.

The reference does this in int64 with 128-bit intermediates (bits.Mul64);
Python integers are arbitrary-precision, so the math here is exactly
equivalent. This runs at control-plane cadence (quota/request changes), not in
the scheduling hot path — matching the reference, where GroupQuotaManager
caches runtimeQuota between updates. The hot-path admission check runs on
device via :mod:`koordinator_tpu.quota.admission`.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable

import numpy as np

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS

#: "no limit" sentinel for max (reference: resource absent from Max means
#: unbounded and unchecked at admission).
UNBOUNDED = -1

ROOT = "root"


@dataclasses.dataclass
class QuotaNode:
    name: str
    parent: str
    min: np.ndarray            # (R,) int64
    max: np.ndarray            # (R,) int64, UNBOUNDED = no cap
    shared_weight: np.ndarray  # (R,) int64; defaults to max (reference default)
    guarantee: np.ndarray      # (R,) int64
    allow_lent: bool = True
    #: opt-in to proportional min shrinking when the parent's resource can no
    #: longer cover the children's min sum (scale_minquota_when_over_root_res
    #: semantics; annotation-driven in the reference)
    enable_scale_min: bool = False
    # computed:
    request: np.ndarray = None         # (R,) raw request (pods or children)
    limited_request: np.ndarray = None # (R,) min(request, max)
    runtime: np.ndarray = None         # (R,)
    used: np.ndarray = None            # (R,)
    non_preemptible_used: np.ndarray = None

    def __post_init__(self):
        z = np.zeros(NUM_RESOURCE_DIMS, dtype=np.int64)
        for f in ("request", "limited_request", "runtime", "used",
                  "non_preemptible_used"):
            if getattr(self, f) is None:
                setattr(self, f, z.copy())


def hamilton_deltas(
    pool: int, total_weight: int, weights: list[int], names: list[str]
) -> list[int]:
    """Largest-remainder split of ``pool`` proportional to ``weights``.

    Exact parity with computeHamiltonDeltas (:194): zero-weight entries get
    nothing; residual +1s go to the largest remainders, ties by name asc.
    """
    n = len(weights)
    deltas = [0] * n
    if total_weight <= 0 or pool <= 0 or n == 0:
        return deltas
    remainders = []
    distributed = 0
    for i, w in enumerate(weights):
        if w <= 0:
            continue
        prod = w * pool  # arbitrary precision == the reference's 128-bit path
        base, rem = divmod(prod, total_weight)
        deltas[i] = base
        distributed += base
        remainders.append((i, rem, names[i]))
    residual = pool - distributed
    if residual <= 0 or not remainders:
        return deltas
    remainders.sort(key=lambda e: (-e[1], e[2]))
    for i in range(min(residual, len(remainders))):
        deltas[remainders[i][0]] += 1
    return deltas


class QuotaTree:
    """Hierarchical quota tree with koordinator's runtime semantics."""

    def __init__(self, total_resource: np.ndarray,
                 scale_min_enabled: bool = False):
        self.total_resource = np.asarray(total_resource, dtype=np.int64)
        self.nodes: dict[str, QuotaNode] = {}
        self.children: dict[str, list[str]] = {ROOT: []}
        #: EnableScaleMinQuota feature gate (GroupQuotaManager
        #: scaleMinQuotaEnabled): shrink enable_scale_min children's min
        #: proportionally when a parent's resource drops below the min sum
        self.scale_min_enabled = scale_min_enabled
        # runtime cache: the reference recomputes runtimeQuota only when
        # quota specs or requests change (core/group_quota_manager.go keeps
        # runtime between updates); we fingerprint every input of the
        # water-filling and skip refresh_runtime when nothing moved
        self._runtime_key: tuple | None = None
        self.runtime_refreshes = 0

    def add(
        self,
        name: str,
        min: np.ndarray,
        max: np.ndarray,
        parent: str = ROOT,
        shared_weight: np.ndarray | None = None,
        guarantee: np.ndarray | None = None,
        allow_lent: bool = True,
        enable_scale_min: bool = False,
    ) -> None:
        if name in self.nodes or name == ROOT:
            raise ValueError(f"quota {name!r} already exists")
        if parent != ROOT and parent not in self.nodes:
            raise ValueError(f"parent quota {parent!r} not found")
        mn = np.asarray(min, dtype=np.int64)
        mx = np.asarray(max, dtype=np.int64)
        # sharedWeight defaults to max (reference: GetSharedWeight falls back
        # to Max when the annotation is absent); UNBOUNDED dims weigh as the
        # cluster total.
        if shared_weight is None:
            sw = np.where(mx == UNBOUNDED, self.total_resource, mx)
        else:
            sw = np.asarray(shared_weight, dtype=np.int64)
        g = (np.zeros(NUM_RESOURCE_DIMS, np.int64) if guarantee is None
             else np.asarray(guarantee, dtype=np.int64))
        self.nodes[name] = QuotaNode(
            name=name, parent=parent, min=mn, max=mx,
            shared_weight=sw, guarantee=g, allow_lent=allow_lent,
            enable_scale_min=enable_scale_min,
        )
        self.children.setdefault(name, [])
        self.children[parent].append(name)

    def set_request(self, name: str, request: np.ndarray) -> None:
        """Set a leaf quota's raw pod-request sum."""
        self.nodes[name].request = np.asarray(request, dtype=np.int64)

    def set_used(self, name: str, used: np.ndarray,
                 non_preemptible: np.ndarray | None = None) -> None:
        self.nodes[name].used = np.asarray(used, dtype=np.int64)
        if non_preemptible is not None:
            self.nodes[name].non_preemptible_used = np.asarray(
                non_preemptible, dtype=np.int64
            )

    # -- request aggregation ------------------------------------------------

    def aggregate_requests(self) -> None:
        """limitedRequest = min(request, max) per node; parents' request =
        sum of children's limitedRequest (reference groupReqLimit model)."""
        for name in self._topo_order(reverse=True):
            node = self.nodes[name]
            kids = self.children[name]
            if kids:
                node.request = np.sum(
                    [self.nodes[k].limited_request for k in kids], axis=0,
                    dtype=np.int64,
                )
            node.limited_request = np.where(
                node.max == UNBOUNDED, node.request,
                np.minimum(node.request, node.max),
            )

    # -- runtime ------------------------------------------------------------

    def _fingerprint(self) -> tuple:
        """Every input of the runtime computation, cheap to compare."""
        rows = tuple(
            (name, n.parent,
             # parents' request is derived by aggregation — only leaf
             # requests are true inputs
             n.request.tobytes() if not self.children[name] else b"",
             n.min.tobytes(), n.max.tobytes(), n.shared_weight.tobytes(),
             n.guarantee.tobytes(), n.allow_lent, n.enable_scale_min)
            for name, n in sorted(self.nodes.items())
        )
        return (self.total_resource.tobytes(), self.scale_min_enabled, rows)

    def refresh_runtime(self, force: bool = False) -> bool:
        """Recompute every node's runtime, top-down. No-ops (returns False)
        when no spec/request input changed since the last refresh."""
        key = self._fingerprint()
        if not force and key == self._runtime_key:
            return False
        self.aggregate_requests()
        self._redistribute(self.children[ROOT], self.total_resource)
        for name in self._topo_order():
            kids = self.children[name]
            if kids:
                self._redistribute(kids, self.nodes[name].runtime)
        self._runtime_key = key
        self.runtime_refreshes += 1
        return True

    def _scaled_mins(
        self, names: list[str], total: np.ndarray
    ) -> dict[str, np.ndarray]:
        """Effective per-child min after scale-min-when-over-root-res.

        Per dimension where the children's min sum exceeds the group's total:
        non-scaling children keep their full min; the remainder (total minus
        their sum, floored at 0) is split over scaling children proportional
        to their original min (getScaledMinQuota semantics, floor division).
        """
        mins = {n: self.nodes[n].min.copy() for n in names}
        if not self.scale_min_enabled:
            return mins
        enable = [n for n in names if self.nodes[n].enable_scale_min]
        if not enable:
            return mins
        disable_sum = np.zeros(NUM_RESOURCE_DIMS, np.int64)
        enable_sum = np.zeros(NUM_RESOURCE_DIMS, np.int64)
        for n in names:
            if self.nodes[n].enable_scale_min:
                enable_sum += self.nodes[n].min
            else:
                disable_sum += self.nodes[n].min
        need_scale = (disable_sum + enable_sum) > total
        if not need_scale.any():
            return mins
        avail = np.maximum(total - disable_sum, 0)
        for n in enable:
            orig = self.nodes[n].min
            scaled = np.where(
                enable_sum > 0, avail * orig // np.maximum(enable_sum, 1), 0
            )
            mins[n] = np.where(need_scale, scaled, orig).astype(np.int64)
        return mins

    def _redistribute(self, names: list[str], total: np.ndarray) -> None:
        """redistribution() (:119) independently per resource dimension."""
        # deterministic order = name asc (map iteration in Go is unordered but
        # Hamilton ties are name-broken; we sort for reproducibility)
        names = sorted(names)
        for node in (self.nodes[n] for n in names):
            node.runtime = np.zeros(NUM_RESOURCE_DIMS, dtype=np.int64)
        eff_min = self._scaled_mins(names, np.asarray(total, np.int64))
        for dim in range(NUM_RESOURCE_DIMS):
            self._redistribute_dim(names, int(total[dim]), dim, eff_min)

    def _redistribute_dim(
        self, names: list[str], total: int, dim: int,
        eff_min: dict[str, np.ndarray] | None = None,
    ) -> None:
        to_partition = total
        hungry: list[QuotaNode] = []
        total_weight = 0
        for node in (self.nodes[n] for n in names):
            base_min = (
                int(eff_min[node.name][dim]) if eff_min is not None
                else int(node.min[dim])
            )
            auto_min = max(base_min, int(node.guarantee[dim]))
            request = int(node.limited_request[dim])
            if request > auto_min:
                hungry.append(node)
                total_weight += int(node.shared_weight[dim])
                node.runtime[dim] = auto_min
            else:
                node.runtime[dim] = request if node.allow_lent else auto_min
            to_partition -= int(node.runtime[dim])
        if to_partition > 0:
            self._iterate_dim(to_partition, total_weight, hungry, dim)

    def _iterate_dim(
        self, pool: int, total_weight: int, nodes: list[QuotaNode], dim: int
    ) -> None:
        while pool > 0 and total_weight > 0 and nodes:
            deltas = hamilton_deltas(
                pool, total_weight,
                [int(n.shared_weight[dim]) for n in nodes],
                [n.name for n in nodes],
            )
            still_hungry: list[QuotaNode] = []
            next_weight = 0
            returned = 0
            for node, delta in zip(nodes, deltas):
                node.runtime[dim] += delta
                request = int(node.limited_request[dim])
                if node.runtime[dim] < request:
                    still_hungry.append(node)
                    next_weight += int(node.shared_weight[dim])
                else:
                    returned += int(node.runtime[dim]) - request
                    node.runtime[dim] = request
            pool, total_weight, nodes = returned, next_weight, still_hungry

    # -- traversal ----------------------------------------------------------

    def _topo_order(self, reverse: bool = False) -> Iterable[str]:
        order: list[str] = []
        stack = list(self.children[ROOT])
        while stack:
            name = stack.pop()
            order.append(name)
            stack.extend(self.children[name])
        return reversed(order) if reverse else order

    def ancestors(self, name: str, include_self: bool = True) -> list[str]:
        chain = [name] if include_self else []
        cur = self.nodes[name].parent
        while cur != ROOT:
            chain.append(cur)
            cur = self.nodes[cur].parent
        return chain

    def runtime_of(self, name: str) -> np.ndarray:
        return self.nodes[name].runtime

    def admits(
        self,
        name: str,
        request: np.ndarray,
        non_preemptible: bool = False,
        check_parents: bool = True,
    ) -> bool:
        """Host-side mirror of admission.quota_admission_mask for one pod
        (checkQuotaRecursive, elasticquota/plugin.go:256-304): used + request
        <= runtime on the pod's quota's declared max dims, up the chain."""
        node = self.nodes.get(name)
        if node is None:
            return True  # no quota: always admitted
        req = np.asarray(request, dtype=np.int64)
        checked = (node.max != UNBOUNDED) & (req > 0)
        chain = self.ancestors(name) if check_parents else [name]
        for anc in chain:
            a = self.nodes[anc]
            if np.any(checked & (a.used + req > a.runtime)):
                return False
        if non_preemptible and np.any(
            checked & (node.non_preemptible_used + req > node.min)
        ):
            return False
        return True
