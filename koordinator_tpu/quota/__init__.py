"""Hierarchical elastic quota: min/max tree, fair-share runtime, admission.

Mirrors the reference's elasticquota core (SURVEY.md section 2.4):

- ``tree``      -- the quota tree + runtime redistribution (water-filling with
                   Hamilton largest-remainder apportionment), exact integer
                   math on the host (control-plane cadence, like the
                   reference's GroupQuotaManager).
- ``admission`` -- the scheduling-hot-path admission check as a device kernel
                   over precomputed ancestor-chain headroom tensors.
"""

from koordinator_tpu.quota.tree import QuotaTree
from koordinator_tpu.quota.admission import (
    QuotaDeviceState,
    quota_admission_mask,
    charge_quota,
    charge_quota_batch,
)

__all__ = [
    "QuotaTree",
    "QuotaDeviceState",
    "quota_admission_mask",
    "charge_quota",
    "charge_quota_batch",
]
