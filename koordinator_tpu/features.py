"""Feature gates (reference: ``pkg/features/`` — k8s component-base
featuregate wrapper; per-module gates in koordlet/runtimehooks).

One process-global :class:`FeatureGates` registry with per-gate defaults;
``--feature-gates=Name=true,...``-style overrides via :meth:`set_from_spec`.
Gate names mirror the reference inventory (SURVEY.md §2.10).
"""

from __future__ import annotations

import threading


class FeatureGates:
    def __init__(self, defaults: dict[str, bool]):
        self._defaults = dict(defaults)
        self._overrides: dict[str, bool] = {}
        self._lock = threading.Lock()

    def enabled(self, name: str) -> bool:
        with self._lock:
            if name in self._overrides:
                return self._overrides[name]
            if name not in self._defaults:
                raise KeyError(f"unknown feature gate {name!r}")
            return self._defaults[name]

    def set(self, name: str, value: bool) -> None:
        with self._lock:
            if name not in self._defaults:
                raise KeyError(f"unknown feature gate {name!r}")
            self._overrides[name] = value

    def set_from_spec(self, spec: str) -> None:
        """Parse 'A=true,B=false' (the --feature-gates flag format)."""
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            name, _, value = part.partition("=")
            self.set(name.strip(), value.strip().lower() in ("true", "1", "yes"))

    def known(self) -> dict[str, bool]:
        with self._lock:
            out = dict(self._defaults)
            out.update(self._overrides)
            return out


# koordlet gates (pkg/features/koordlet_features.go)
KOORDLET_GATES = FeatureGates({
    "AuditEvents": True,
    "AuditEventsHTTPHandler": False,
    "BECPUSuppress": True,
    "BECPUManager": False,
    "BECPUEvict": False,
    "BEMemoryEvict": False,
    "CPUEvict": False,
    "MemoryEvict": False,
    "CPUBurst": True,
    "SystemConfig": False,
    "RdtResctrl": True,
    "CgroupReconcile": False,
    "NodeTopologyReport": True,
    "Accelerators": False,
    "RDMADevices": False,
    "CPICollector": False,
    "Libpfm4": False,
    "CPUAllocatableEvict": False,
    "MemoryAllocatableEvict": False,
    "HamiCoreVGPUMonitor": False,
    "ResctrlCollector": False,
    "PSICollector": True,
    "BlkIOReconcile": False,
    "ColdPageCollector": False,
    "HugePageReport": False,
    "PodResourcesProxy": False,
    "PerCPUMetric": False,
})

# runtimehooks gates (pkg/koordlet/runtimehooks/config.go)
RUNTIMEHOOK_GATES = FeatureGates({
    "GroupIdentity": True,
    "CPUSetAllocator": True,
    "GPUEnvInject": False,
    "RDMADeviceInject": False,
    "BatchResource": True,
    "CoreSched": False,
    "CPUNormalization": False,
    "Resctrl": False,
    "TCNetworkQoS": False,
    "TerwayQoS": False,
})

# manager/scheduler gates (pkg/features/features.go, scheduler_features.go)
SCHEDULER_GATES = FeatureGates({
    "MultiQuotaTree": False,
    "ElasticQuotaGuaranteeUsage": False,
    "ElasticQuotaEnableUpdateResourceKey": False,
    "ResizePod": False,
    "LazyReservationRestore": False,
    "DevicePluginAdaption": False,
    "CrossSchedulerNomination": False,
    "SyncBarrier": True,
    "GangPendingPodsConditionPatch": False,
    "ColocationProfileSkipMutatingHandler": False,
    "WebhookFramework": True,
})
