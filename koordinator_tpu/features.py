"""Feature gates (reference: ``pkg/features/`` — k8s component-base
featuregate wrapper; per-module gates in koordlet/runtimehooks).

One process-global :class:`FeatureGates` registry with per-gate defaults;
``--feature-gates=Name=true,...``-style overrides via :meth:`set_from_spec`.
Gate names mirror the reference inventory (SURVEY.md §2.10).
"""

from __future__ import annotations

import threading


class FeatureGates:
    def __init__(self, defaults: dict[str, bool]):
        self._defaults = dict(defaults)
        self._overrides: dict[str, bool] = {}
        self._lock = threading.Lock()

    def enabled(self, name: str) -> bool:
        with self._lock:
            if name in self._overrides:
                return self._overrides[name]
            if name not in self._defaults:
                raise KeyError(f"unknown feature gate {name!r}")
            return self._defaults[name]

    def set(self, name: str, value: bool) -> None:
        with self._lock:
            if name not in self._defaults:
                raise KeyError(f"unknown feature gate {name!r}")
            self._overrides[name] = value

    def set_from_spec(self, spec: str) -> None:
        """Parse 'A=true,B=false' (the --feature-gates flag format)."""
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            name, _, value = part.partition("=")
            self.set(name.strip(), value.strip().lower() in ("true", "1", "yes"))

    def known(self) -> dict[str, bool]:
        with self._lock:
            out = dict(self._defaults)
            out.update(self._overrides)
            return out


# koordlet gates — defaults mirror the reference table row for row
# (pkg/features/koordlet_features.go:214-242)
KOORDLET_GATES = FeatureGates({
    "AuditEvents": False,
    "AuditEventsHTTPHandler": False,
    "BECPUSuppress": True,
    "BECPUManager": False,
    "BECPUEvict": False,
    "BEMemoryEvict": False,
    "CPUEvict": False,
    "MemoryEvict": False,
    "CPUBurst": True,
    "SystemConfig": False,
    "RdtResctrl": True,
    "CgroupReconcile": False,
    "NodeTopologyReport": True,
    "Accelerators": False,
    "RDMADevices": False,
    "CPICollector": False,
    "Libpfm4": False,
    "CPUAllocatableEvict": False,
    "MemoryAllocatableEvict": False,
    "HamiCoreVGPUMonitor": False,
    "ResctrlCollector": False,
    "PSICollector": False,
    "BlkIOReconcile": False,
    "ColdPageCollector": False,
    "HugePageReport": False,
    "PodResourcesProxy": False,
    "PerCPUMetric": False,
})

# runtimehooks gates (pkg/koordlet/runtimehooks/config.go)
RUNTIMEHOOK_GATES = FeatureGates({
    "GroupIdentity": True,
    "CPUSetAllocator": True,
    "GPUEnvInject": False,
    "RDMADeviceInject": False,
    "BatchResource": True,
    "CoreSched": False,
    "CPUNormalization": False,
    "Resctrl": False,
    "TCNetworkQoS": False,
    "TerwayQoS": False,
})

# manager/scheduler gates — the union of the reference's two tables
# (pkg/features/features.go:118-169 and scheduler_features.go:146-171;
# overlapping names carry identical defaults in both).  The reference's
# vendored-k8s informer-compat shims (Compatible*/Disable*Informer and
# the GA leftovers CSIStorageCapacity/GenericEphemeralVolume/
# PodDisruptionBudget) are included for flag-surface parity even though
# this design has no client-go informers behind them.
SCHEDULER_GATES = FeatureGates({
    # webhook surface (features.go)
    "PodMutatingWebhook": True,
    "PodValidatingWebhook": True,
    "ElasticQuotaMutatingWebhook": True,
    "ElasticQuotaValidatingWebhook": True,
    "NodeMutatingWebhook": False,
    "NodeValidatingWebhook": False,
    "ConfigMapValidatingWebhook": False,
    "ReservationMutatingWebhook": False,
    "WebhookFramework": True,
    "ColocationProfileSkipMutatingResources": False,
    "ColocationProfileSkipValidatingPriority": False,
    "BindingAdmissionWebhook": False,
    "ValidatePodDeviceResource": False,
    "EnablePodEnhancedValidator": False,
    "DisableExtendedResourceSpec": False,
    "DisableDeviceResourceSpec": False,
    # quota (features.go + scheduler_features.go)
    "MultiQuotaTree": False,
    "ElasticQuotaIgnorePodOverhead": False,
    "ElasticQuotaIgnoreTerminatingPod": False,
    "ElasticQuotaImmediateIgnoreTerminatingPod": False,
    "ElasticQuotaGuaranteeUsage": False,
    "ElasticQuotaEnableUpdateResourceKey": False,
    "ElasticQuotaEvaluationTransformPod": False,
    "DisableDefaultQuota": False,
    "SupportParentQuotaSubmitPod": False,
    "EnableQuotaAdmission": False,
    # manager controllers / transformers (features.go)
    "EnableSyncGPUSharedResource": False,
    "ColocationProfileController": False,
    "DisablePVCReservation": False,
    "PriorityTransformer": False,
    "PreemptionPolicyTransformer": False,
    "ReplaceResourcesTransformer": False,
    # scheduler (scheduler_features.go)
    "CompatibleCSIStorageCapacity": False,
    "DisableCSIStorageCapacityInformer": False,
    "CompatiblePodDisruptionBudget": False,
    "DisablePodDisruptionBudgetInformer": False,
    "DisableDynamicResourceAllocationInformer": False,
    "ResizePod": False,
    "LazyReservationRestore": False,
    "OmitNodeLabelsForReservation": False,
    "SkipReservationFitsNode": False,
    "DevicePluginAdaption": False,
    "CleanExpiredReservationAllocated": False,
    "SkipFilterWithNominatedPods": False,
    "DynamicSchedulerCheck": True,
    "CSIStorageCapacity": True,
    "GenericEphemeralVolume": True,
    "PodDisruptionBudget": True,
    "SyncBarrier": False,
    "CrossSchedulerNomination": False,
    "GangPendingPodsConditionPatch": True,
})
