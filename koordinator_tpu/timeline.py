"""Per-cycle timeline reconstruction + host-wait attribution (ISSUE 18).

ROADMAP item 5 names the host/wire plane as the speed ceiling, and the
only signal so far was one scalar — ``pipeline_host_wait_fraction``,
the share of a cycle's wall the host spent blocked on device solve
results.  This module is the measurement plane underneath it: every
hot spot on the host path records a typed **segment** (two
``perf_counter`` reads + one deque append), and at the end of each
TenantScheduler cycle (or standalone round) the recorder reconstructs
a gantt of the window, attributes every instant of wall time to a
cause, derives device-idle intervals from the dispatch/block edges,
and names the cycle's **critical path**.

Segment causes (also the attribution priority, highest first — at any
instant the most specific active segment wins):

====================  =====================================================
``device_block``      host blocked in ``jax.block_until_ready`` — by
                      construction this bucket equals
                      ``pipeline_host_wait_fraction`` (same intervals the
                      ``_solve_device_s`` accumulator sums)
``lock_wait``         waiting to acquire a scheduler round lock
``json_codec``        wire payload encode/decode (``transport/wire.py``)
``deltasync_apply``   a sync event batch applying onto a binding
``dispatch``          host-side solve dispatch work (``_round_dispatch``)
``build_batch``       the BatchBuild phase (``_build_batch``)
``bind_commit``       the Bind phase (``_commit_bind`` loop)
``host_other``        any other monitor phase (Reservations, Solve's
                      host share, Reserve, Diagnose, PostFilter, ...)
====================  =====================================================

Wall time covered by NO segment lands in the explicit ``unattributed``
residual — the phase-accounting invariant test asserts it stays under
5% of the cycle, so silently untimed host work can never reappear.

``device_busy`` segments are NOT host work: they mark the device
executing between a dispatch edge and its block edge, and only feed
the ``device_idle_fraction`` derivation.

**Attribution semantics.** ``host_wait_attribution{cause}`` decomposes
the WHOLE cycle wall into fractions that sum to 1.0 (including
``unattributed``).  The ``device_block`` bucket equals
``pipeline_host_wait_fraction`` (same clock, same intervals); the
remaining causes decompose its complement — the host share the ROADMAP
item-5 attack has to shrink.

**Kill switch.**  ``KOORD_TIMELINE=0`` in the environment (read once at
import) or ``--no-timeline`` on the scheduler binary disables the
recorder: every hook degrades to one attribute read, no segment is
stored, and scheduling decisions are bit-identical (the instrumentation
is pure host-side timing — it never touches solve inputs either way).

Everything here is stdlib-only and thread-safe: segments arrive from
the cycle thread, RPC reader threads (deltasync applies, wire codec),
and gateway threads concurrently.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from collections import deque

#: attribution priority, most specific first (see the module docstring)
CAUSES = ("device_block", "lock_wait", "json_codec", "deltasync_apply",
          "dispatch", "build_batch", "bind_commit", "host_other")
#: the residual bucket: wall time no segment covered
UNATTRIBUTED = "unattributed"
#: every label the host_wait_attribution family republishes per cycle
ATTRIBUTION_CAUSES = CAUSES + (UNATTRIBUTED,)
#: device-occupancy marker (feeds device_idle_fraction, not attribution)
DEVICE_BUSY = "device_busy"

_PRIORITY = {cause: i for i, cause in enumerate(CAUSES)}

#: monitor phase name -> attribution cause (anything unlisted is
#: host_other; the phase name survives on the segment for the gantt)
PHASE_CAUSES = {"BatchBuild": "build_batch", "Bind": "bind_commit"}


def _merge_intervals(intervals: list[tuple[float, float]]
                     ) -> list[tuple[float, float]]:
    """Union of [start, end) intervals, sorted and coalesced."""
    merged: list[list[float]] = []
    for s, e in sorted(intervals):
        if e <= s:
            continue
        if merged and s <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], e)
        else:
            merged.append([s, e])
    return [(s, e) for s, e in merged]


def sweep_attribution(segments: list[dict], t0: float, t1: float
                      ) -> tuple[dict, list[dict]]:
    """Attribute every instant of [t0, t1] to exactly one cause.

    An event sweep over the segment boundaries: at each instant the
    highest-priority active segment's cause wins (nesting puts the
    specific segment — a block wait inside the Solve phase, a codec
    call inside a deltasync apply — above its container).  Returns
    ``(seconds_by_cause, chain)`` where the chain is the covering
    sequence of maximal same-cause intervals — the cycle's critical
    path, since the cycle runs to completion and at every instant the
    chain names what the wall clock was spent on.  This runs once per
    cycle on the scheduling thread, so it is O(n log n) in segments,
    not elementary-intervals x segments.
    """
    totals = {cause: 0.0 for cause in ATTRIBUTION_CAUSES}
    if t1 <= t0:
        return totals, []
    events: list[tuple[float, int, int, str]] = []
    for s in segments:
        prio = _PRIORITY.get(s["cause"])
        if prio is None:
            continue
        start, end = max(s["start"], t0), min(s["end"], t1)
        if end <= start:
            continue
        events.append((start, 1, prio, s["name"]))
        events.append((end, -1, prio, s["name"]))
    events.sort(key=lambda e: e[0])
    counts = [0] * len(CAUSES)
    names: list[list[str]] = [[] for _ in CAUSES]
    chain: list[dict] = []

    def emit(lo: float, hi: float) -> None:
        if hi <= lo:
            return
        best = next((p for p, c in enumerate(counts) if c), None)
        if best is None:
            cause, name = UNATTRIBUTED, ""
        else:
            cause, name = CAUSES[best], names[best][-1]
        totals[cause] += hi - lo
        if chain and chain[-1]["cause"] == cause:
            chain[-1]["end"] = hi
        else:
            chain.append({"start": lo, "end": hi,
                          "cause": cause, "name": name})

    prev = t0
    i, n = 0, len(events)
    while i < n:
        now = events[i][0]
        emit(prev, now)
        while i < n and events[i][0] == now:
            _, delta, prio, name = events[i]
            if delta > 0:
                counts[prio] += 1
                names[prio].append(name)
            else:
                counts[prio] -= 1
                names[prio].remove(name)
            i += 1
        prev = now
    emit(prev, t1)
    return totals, chain


def device_idle(segments: list[dict], t0: float, t1: float
                ) -> tuple[list[tuple[float, float]], float]:
    """Idle intervals = the cycle window minus the union of
    ``device_busy`` spans (each one a dispatch edge to its block
    edge).  Returns ``(idle_intervals, busy_seconds)``."""
    busy = _merge_intervals([
        (max(s["start"], t0), min(s["end"], t1)) for s in segments
        if s["cause"] == DEVICE_BUSY and s["end"] > t0 and s["start"] < t1])
    idle: list[tuple[float, float]] = []
    cursor = t0
    for s, e in busy:
        if s > cursor:
            idle.append((cursor, s))
        cursor = max(cursor, e)
    if cursor < t1:
        idle.append((cursor, t1))
    return idle, sum(e - s for s, e in busy)


class TimelineRecorder:
    """Lock-protected segment sink + per-cycle reconstruction ring.

    One module-level instance (:data:`RECORDER`) serves every
    scheduler in the process — segments carry a tenant tag, cycle
    windows clip by time, and the ring backs ``/debug/timeline``.
    """

    def __init__(self, enabled: bool = True, max_segments: int = 16384,
                 max_cycles: int = 64):
        self._enabled = enabled
        self._lock = threading.Lock()
        self._segments: deque = deque(maxlen=max_segments)
        self._cycles: deque = deque(maxlen=max_cycles)

    # -- the hot-path surface -------------------------------------------

    @property
    def enabled(self) -> bool:
        return self._enabled

    def set_enabled(self, enabled: bool) -> None:
        """The kill switch: disabling drops pending segments so a
        re-enable can't attribute a stale window."""
        self._enabled = bool(enabled)
        with self._lock:
            self._segments.clear()

    def add(self, start: float, end: float, cause: str,
            name: str = "", tenant: str = "") -> None:
        """Record one finished segment (perf_counter timestamps)."""
        if not self._enabled or end <= start:
            return
        with self._lock:
            self._segments.append((start, end, cause, name, tenant))

    @contextlib.contextmanager
    def section(self, cause: str, name: str = "", tenant: str = ""):
        """Time a block as one segment; near-free when disabled."""
        if not self._enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(t0, time.perf_counter(), cause, name, tenant)

    # -- cycle reconstruction -------------------------------------------

    def _window(self, t0: float, t1: float) -> list[dict]:
        with self._lock:
            raw = [s for s in self._segments if s[1] > t0 and s[0] < t1]
            # prune consumed history: segments entirely before this
            # window belong to no future cycle (inter-cycle applies
            # attribute nowhere by design)
            while self._segments and self._segments[0][1] <= t1:
                self._segments.popleft()
        return [{"start": max(s, t0), "end": min(e, t1), "cause": c,
                 "name": n, "tenant": t}
                for s, e, c, n, t in raw]

    def finish_cycle(self, cycle: int, t0: float, t1: float,
                     mode: str = "cycle", publish: bool = True) -> dict | None:
        """Reconstruct the window [t0, t1]: clip segments, attribute
        wall time, derive device idle, name the critical path; append
        the cycle doc to the ring and (by default) republish the
        ``host_wait_attribution`` / ``device_idle_fraction`` /
        ``critical_path_seconds`` gauges.  Returns the doc (None when
        disabled or the window is degenerate)."""
        if not self._enabled or t1 <= t0:
            return None
        wall = t1 - t0
        segments = self._window(t0, t1)
        totals, chain = sweep_attribution(segments, t0, t1)
        idle, busy_s = device_idle(segments, t0, t1)
        attribution = {c: totals[c] / wall for c in ATTRIBUTION_CAUSES}
        named = {c: s for c, s in totals.items()
                 if c != UNATTRIBUTED and s > 0.0}
        critical_cause = (max(named, key=named.get) if named
                          else UNATTRIBUTED)
        doc = {
            "cycle": cycle,
            "mode": mode,
            "start": t0,
            "wall_s": wall,
            "segments": [
                {"start": s["start"] - t0, "end": s["end"] - t0,
                 "cause": s["cause"], "name": s["name"],
                 "tenant": s["tenant"]}
                for s in sorted(segments, key=lambda s: s["start"])],
            "attribution": attribution,
            "attribution_s": totals,
            "unattributed_fraction": attribution[UNATTRIBUTED],
            "device_busy_s": busy_s,
            "device_idle_fraction": (wall - busy_s) / wall,
            "device_idle": [(s - t0, e - t0) for s, e in idle],
            "critical_path": [
                {"start": c["start"] - t0, "end": c["end"] - t0,
                 "cause": c["cause"], "name": c["name"]}
                for c in chain],
            "critical_cause": critical_cause,
            "critical_seconds": totals.get(critical_cause, 0.0),
        }
        with self._lock:
            self._cycles.append(doc)
        if publish:
            self._publish(doc)
        return doc

    @staticmethod
    def _publish(doc: dict) -> None:
        from koordinator_tpu import metrics

        for cause in ATTRIBUTION_CAUSES:
            # every cause republished each cycle so cleared ones read 0
            metrics.host_wait_attribution.set(
                doc["attribution"][cause], labels={"cause": cause})
            metrics.critical_path_seconds.set(
                doc["attribution_s"][cause], labels={"cause": cause})
        metrics.device_idle_fraction.set(doc["device_idle_fraction"])

    def cycles(self, limit: int = 8) -> list[dict]:
        """Newest-first cycle docs (the /debug/timeline body)."""
        with self._lock:
            out = list(self._cycles)[-max(limit, 0):]
        out.reverse()
        return out

    def reset_for_tests(self) -> None:
        with self._lock:
            self._segments.clear()
            self._cycles.clear()


#: process-wide recorder; KOORD_TIMELINE=0 disables at import (the env
#: half of the kill switch — --no-timeline is the CLI half)
RECORDER = TimelineRecorder(
    enabled=os.environ.get("KOORD_TIMELINE", "1") != "0")
