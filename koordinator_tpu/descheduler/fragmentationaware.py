"""FragmentationAware: rebalance nodes whose resource dimensions are
unevenly consumed.

Semantics from ``pkg/descheduler/framework/plugins/fragmentationaware/
scoring.go``:

- ``scoreNodeImbalance`` (scoring.go:63): per node, the *population* standard
  deviation of the requested/allocatable fractions across the configured
  resource dimensions; dimensions with zero allocatable are skipped
  (scoring.go:33 — divide-by-zero guard).
- ``scorePodRemovalGain`` (scoring.go:80): stddev(before) - stddev(after
  removing the pod); a large positive gain means the pod is what skews the
  node.

The reference computes these per (node, pod) in Go loops; here both are
batched tensor kernels over the same (N, R)/(P, R) milli-unit request
tensors the scheduler already holds, and victim selection is a scan that
replays evictions so later gains see earlier removals.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS, ResourceDim


def default_resource_mask() -> jnp.ndarray:
    """(R,) bool — which dimensions participate (reference default:
    cpu + memory; custom resources opt in via config)."""
    mask = jnp.zeros(NUM_RESOURCE_DIMS, bool)
    return mask.at[ResourceDim.CPU].set(True).at[ResourceDim.MEMORY].set(True)


def node_imbalance(
    requested: jnp.ndarray,      # (N, R) int32 milli-units requested
    allocatable: jnp.ndarray,    # (N, R) int32 milli-units allocatable
    resource_mask: jnp.ndarray,  # (R,) bool configured dimensions
) -> jnp.ndarray:
    """(N,) float32 — population stddev of allocation fractions
    (scoring.go:63 scoreNodeImbalance)."""
    valid = resource_mask[None, :] & (allocatable > 0)            # (N, R)
    frac = jnp.where(
        valid, requested.astype(jnp.float32) / jnp.maximum(allocatable, 1), 0.0
    )
    count = jnp.sum(valid, axis=-1)                               # (N,)
    safe = jnp.maximum(count, 1)
    mean = jnp.sum(frac, axis=-1) / safe
    var = jnp.sum(jnp.where(valid, (frac - mean[:, None]) ** 2, 0.0), axis=-1)
    return jnp.where(count > 0, jnp.sqrt(var / safe), 0.0)


def removal_gains(
    requested: jnp.ndarray,      # (N, R)
    allocatable: jnp.ndarray,    # (N, R)
    pod_node: jnp.ndarray,       # (P,) int32; -1 = unbound
    pod_requests: jnp.ndarray,   # (P, R)
    resource_mask: jnp.ndarray,  # (R,)
) -> jnp.ndarray:
    """(P,) float32 — stddev gain from removing each pod from its node,
    all pods at once (scoring.go:80 scorePodRemovalGain)."""
    node = jnp.maximum(pod_node, 0)
    before = node_imbalance(requested, allocatable, resource_mask)  # (N,)
    after_req = jnp.maximum(requested[node] - pod_requests, 0)      # (P, R)
    after = node_imbalance(after_req, allocatable[node], resource_mask)
    return jnp.where(pod_node >= 0, before[node] - after, 0.0)


def select_victims(
    requested: jnp.ndarray,       # (N, R)
    allocatable: jnp.ndarray,     # (N, R)
    node_valid: jnp.ndarray,      # (N,) bool
    pod_node: jnp.ndarray,        # (P,) int32
    pod_requests: jnp.ndarray,    # (P, R)
    pod_evictable: jnp.ndarray,   # (P,) bool — host-side evictor filter result
    resource_mask: jnp.ndarray,   # (R,)
    imbalance_threshold: float = 0.2,
    min_gain: float = 0.05,
    max_victims: int = 16,
) -> jnp.ndarray:
    """(P,) bool victim mask.

    Greedy highest-gain-first: each accepted eviction updates its node's
    requested tensor, so subsequent gains are measured against the
    already-rebalanced node (the reference recomputes scoreNodeImbalance
    per candidate the same way). A pod is a victim only while its node's
    imbalance still exceeds ``imbalance_threshold`` and its own gain
    exceeds ``min_gain``.
    """
    p = pod_node.shape[0]
    gains = removal_gains(requested, allocatable, pod_node, pod_requests,
                          resource_mask)
    order = jnp.argsort(-gains)   # best gains first

    def step(carry, idx):
        req, taken = carry
        node = pod_node[idx]
        safe = jnp.maximum(node, 0)
        imb_before = node_imbalance(req[safe][None], allocatable[safe][None],
                                    resource_mask)[0]
        after_req = jnp.maximum(req[safe] - pod_requests[idx], 0)
        imb_after = node_imbalance(after_req[None], allocatable[safe][None],
                                   resource_mask)[0]
        accept = (
            (node >= 0)
            & node_valid[safe]
            & pod_evictable[idx]
            & (taken < max_victims)
            & (imb_before > imbalance_threshold)
            & (imb_before - imb_after > min_gain)
        )
        req = req.at[safe].set(jnp.where(accept, after_req, req[safe]))
        return (req, taken + accept.astype(jnp.int32)), accept

    (_, _), accepted = jax.lax.scan(step, (requested, jnp.int32(0)), order)
    return jnp.zeros(p, bool).at[order].set(accepted)
