"""Upstream-port descheduler plugins.

The reference compiles the sigs.k8s.io/descheduler plugin set straight into
its framework through an adaptor (``pkg/descheduler/framework/plugins/
kubernetes/plugin.go:60-132`` registers HighNodeUtilization,
LowNodeUtilization, PodLifeTime, RemoveFailedPods, RemoveDuplicates,
RemovePodsHavingTooManyRestarts, RemovePodsViolatingInterPodAntiAffinity,
RemovePodsViolatingNodeAffinity, RemovePodsViolatingNodeTaints,
RemovePodsViolatingTopologySpreadConstraint; defaultevictor at :139).

Here the same capabilities are rebuilt natively: the per-pod predicate
plugins are small host-side passes (they are O(pods) metadata checks, not
tensor work), while topology-spread balancing and utilization compaction
use vectorized counting over the cluster tensors. All evictions flow
through the profile's EvictorFilter/Evictor like every other plugin.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Optional

import numpy as np

from koordinator_tpu.descheduler.framework import Handle, PodInfo


@dataclasses.dataclass(frozen=True)
class NodeInfo:
    """Descheduler-side node view for the predicate plugins."""

    name: str
    labels: dict = dataclasses.field(default_factory=dict)
    # taints: (key, value, effect) with effect NoSchedule/NoExecute/PreferNoSchedule
    taints: tuple = ()


# ---- matching helpers (upstream descheduler node/pod utils) ----------------

def match_expressions(term, labels: dict) -> bool:
    """ALL (key, op, values) expressions of one term match the labels."""
    for key, op, values in term:
        has = key in labels
        val = labels.get(key)
        if op == "In":
            if not has or val not in values:
                return False
        elif op == "NotIn":
            if has and val in values:
                return False
        elif op == "Exists":
            if not has:
                return False
        elif op == "DoesNotExist":
            if has:
                return False
        else:
            return False
    return True


def pod_fits_node_affinity(pod: PodInfo, node: NodeInfo) -> bool:
    """requiredDuringSchedulingIgnoredDuringExecution check
    (upstream nodeaffinity.PodMatchesNodeSelectorAndAffinityTerms)."""
    for k, v in pod.node_selector.items():
        if node.labels.get(k) != v:
            return False
    if pod.required_affinity:
        return any(match_expressions(term, node.labels)
                   for term in pod.required_affinity)
    return True


def tolerates(pod: PodInfo, taint) -> bool:
    key, value, effect = taint
    for tkey, top, tval, teffect in pod.tolerations:
        if teffect and teffect != effect:
            continue
        if top == "Exists":
            if tkey in ("", key):
                return True
        elif top == "Equal":
            if tkey == key and tval == value:
                return True
    return False


def selector_matches(selector: dict, labels: dict) -> bool:
    return all(labels.get(k) == v for k, v in selector.items())


# ---- predicate plugins -----------------------------------------------------

class PodLifeTime:
    """Deschedule: evict pods older than max_seconds, optionally restricted
    to pod phases/label selector (upstream podlifetime)."""

    name = "PodLifeTime"

    def __init__(self, max_seconds: float, states: Optional[list[str]] = None,
                 selector: Optional[dict] = None, clock=time.time):
        self.max_seconds = max_seconds
        self.states = states
        self.selector = selector or {}
        self.clock = clock

    def deschedule(self, handle: Handle) -> int:
        now = self.clock()
        evicted = 0
        # oldest first, like upstream's sort by creation time
        for pod in sorted(handle.pods(), key=lambda p: p.created):
            if now - pod.created <= self.max_seconds:
                continue
            if self.states and pod.phase not in self.states:
                continue
            if not selector_matches(self.selector, pod.labels):
                continue
            if handle.evict(pod, self.name):
                evicted += 1
        return evicted


class RemoveFailedPods:
    """Deschedule: evict Failed pods, optionally gated on reasons and a
    minimum lifetime (upstream removefailedpods)."""

    name = "RemoveFailedPods"

    def __init__(self, reasons: Optional[list[str]] = None,
                 min_pod_lifetime_seconds: float = 0.0,
                 include_owner_kinds: Optional[list[str]] = None,
                 clock=time.time):
        self.reasons = reasons
        self.min_pod_lifetime_seconds = min_pod_lifetime_seconds
        self.include_owner_kinds = include_owner_kinds
        self.clock = clock

    def deschedule(self, handle: Handle) -> int:
        now = self.clock()
        evicted = 0
        for pod in handle.pods():
            if pod.phase != "Failed":
                continue
            if self.reasons and pod.reason not in self.reasons:
                continue
            if now - pod.created < self.min_pod_lifetime_seconds:
                continue
            if self.include_owner_kinds:
                kind = pod.owner.split("/", 1)[0] if pod.owner else ""
                if kind not in self.include_owner_kinds:
                    continue
            if handle.evict(pod, self.name):
                evicted += 1
        return evicted


class RemovePodsHavingTooManyRestarts:
    """Deschedule: evict pods whose restart count crossed the threshold
    (upstream removepodshavingtoomanyrestarts)."""

    name = "RemovePodsHavingTooManyRestarts"

    def __init__(self, pod_restart_threshold: int,
                 states: Optional[list[str]] = None):
        self.pod_restart_threshold = pod_restart_threshold
        self.states = states

    def deschedule(self, handle: Handle) -> int:
        evicted = 0
        for pod in handle.pods():
            if pod.restart_count < self.pod_restart_threshold:
                continue
            if self.states and pod.phase not in self.states:
                continue
            if handle.evict(pod, self.name):
                evicted += 1
        return evicted


class RemoveDuplicates:
    """Balance: when one node runs several replicas of the same owner with
    the same image set, evict the extras so they respread (upstream
    removeduplicates: duplicates keyed by owner + sorted container images)."""

    name = "RemoveDuplicates"

    def __init__(self, exclude_owner_kinds: Optional[list[str]] = None):
        self.exclude_owner_kinds = exclude_owner_kinds or []

    def balance(self, handle: Handle) -> int:
        groups: dict[tuple, list[PodInfo]] = {}
        for pod in handle.pods():
            if not pod.owner:
                continue
            kind = pod.owner.split("/", 1)[0]
            if kind in self.exclude_owner_kinds:
                continue
            key = (pod.node, pod.namespace, pod.owner,
                   tuple(sorted(pod.images)))
            groups.setdefault(key, []).append(pod)
        evicted = 0
        for pods in groups.values():
            # keep the oldest replica on the node, evict the rest
            for pod in sorted(pods, key=lambda p: p.created)[1:]:
                if handle.evict(pod, self.name):
                    evicted += 1
        return evicted


class RemovePodsViolatingNodeAffinity:
    """Deschedule: evict pods whose node no longer satisfies their required
    node affinity (upstream removepodsviolatingnodeaffinity)."""

    name = "RemovePodsViolatingNodeAffinity"

    def __init__(self, nodes_fn: Callable[[], list[NodeInfo]]):
        self.nodes_fn = nodes_fn

    def deschedule(self, handle: Handle) -> int:
        nodes = {n.name: n for n in self.nodes_fn()}
        evicted = 0
        for pod in handle.pods():
            node = nodes.get(pod.node)
            if node is None:
                continue
            if pod_fits_node_affinity(pod, node):
                continue
            if handle.evict(pod, self.name):
                evicted += 1
        return evicted


class RemovePodsViolatingNodeTaints:
    """Deschedule: evict pods not tolerating their node's NoSchedule taints
    (upstream removepodsviolatingnodetaints)."""

    name = "RemovePodsViolatingNodeTaints"

    def __init__(self, nodes_fn: Callable[[], list[NodeInfo]],
                 include_prefer_no_schedule: bool = False,
                 excluded_taints: Optional[list[str]] = None):
        self.nodes_fn = nodes_fn
        self.include_prefer_no_schedule = include_prefer_no_schedule
        self.excluded_taints = set(excluded_taints or [])

    def _relevant(self, taint) -> bool:
        key, _, effect = taint
        if key in self.excluded_taints:
            return False
        if effect == "NoSchedule":
            return True
        return (effect == "PreferNoSchedule"
                and self.include_prefer_no_schedule)

    def deschedule(self, handle: Handle) -> int:
        nodes = {n.name: n for n in self.nodes_fn()}
        evicted = 0
        for pod in handle.pods():
            node = nodes.get(pod.node)
            if node is None:
                continue
            violated = any(self._relevant(t) and not tolerates(pod, t)
                           for t in node.taints)
            if violated and handle.evict(pod, self.name):
                evicted += 1
        return evicted


class RemovePodsViolatingInterPodAntiAffinity:
    """Deschedule: evict a pod when another pod on the same node owns an
    anti-affinity term matching it (upstream
    removepodsviolatinginterpodantiaffinity.checkPodsWithAntiAffinityExist)."""

    name = "RemovePodsViolatingInterPodAntiAffinity"

    def deschedule(self, handle: Handle) -> int:
        by_node: dict[str, list[PodInfo]] = {}
        for pod in handle.pods():
            by_node.setdefault(pod.node, []).append(pod)
        evicted = 0
        for pods in by_node.values():
            for pod in pods:
                violated = any(
                    other.uid != pod.uid
                    and other.namespace == pod.namespace
                    and any(selector_matches(sel, pod.labels)
                            for sel, _tkey in other.anti_affinity)
                    for other in pods
                )
                if violated and handle.evict(pod, self.name):
                    evicted += 1
        return evicted


# ---- vectorized balance plugins -------------------------------------------

class RemovePodsViolatingTopologySpreadConstraint:
    """Balance: restore maxSkew across topology domains (upstream
    removepodsviolatingtopologyspreadconstraint). Domain counting and the
    above-target overflow computation are vectorized with numpy; eviction
    picks the newest pods from oversized domains."""

    name = "RemovePodsViolatingTopologySpreadConstraint"

    def __init__(self, nodes_fn: Callable[[], list[NodeInfo]]):
        self.nodes_fn = nodes_fn

    def balance(self, handle: Handle) -> int:
        nodes = self.nodes_fn()
        pods = handle.pods()
        # collect the distinct constraints present on pods
        constraints = {}
        for pod in pods:
            for tkey, max_skew, selector in pod.spread_constraints:
                constraints[(tkey, max_skew, tuple(sorted(selector.items())))] = (
                    tkey, max_skew, dict(selector))
        evicted = 0
        for tkey, max_skew, selector in constraints.values():
            domain_of = {n.name: n.labels.get(tkey) for n in nodes}
            domains = sorted({d for d in domain_of.values() if d is not None})
            if not domains:
                continue
            index = {d: i for i, d in enumerate(domains)}
            matching = [p for p in pods
                        if selector_matches(selector, p.labels)
                        and domain_of.get(p.node) in index]
            counts = np.zeros(len(domains), np.int64)
            for p in matching:
                counts[index[domain_of[p.node]]] += 1
            # how many pods each domain must shed for skew <= max_skew:
            # everything above (min + maxSkew)
            target = counts.min() + max_skew
            overflow = np.maximum(counts - target, 0)
            for dom_i in np.nonzero(overflow)[0]:
                dom = domains[dom_i]
                victims = sorted(
                    (p for p in matching if domain_of[p.node] == dom),
                    key=lambda p: -p.created)  # newest first
                for pod in victims[: int(overflow[dom_i])]:
                    if handle.evict(pod, self.name):
                        evicted += 1
        return evicted


class HighNodeUtilization:
    """Balance: compact the cluster — drain nodes whose request-based
    utilization is below the thresholds so their pods repack elsewhere
    (upstream nodeutilization.HighNodeUtilization).

    ``state_fn`` returns (requested(N,R), allocatable(N,R), node_valid(N,),
    node_names[N]); thresholds is a (R,) int percent vector with -1 for
    unconfigured dims. Node classification is one vectorized pass.
    """

    name = "HighNodeUtilization"

    def __init__(
        self,
        state_fn: Callable[[], tuple[np.ndarray, np.ndarray, np.ndarray, list[str]]],
        thresholds: np.ndarray,
        number_of_nodes: int = 0,   # skip when fewer underutilized nodes
    ):
        self.state_fn = state_fn
        self.thresholds = np.asarray(thresholds, np.int32)
        self.number_of_nodes = number_of_nodes

    def underutilized_nodes(self) -> list[str]:
        requested, allocatable, node_valid, node_names = self.state_fn()
        pct = np.where(allocatable > 0,
                       requested * 100 // np.maximum(allocatable, 1), 0)
        configured = self.thresholds >= 0
        under = (np.all((pct < self.thresholds) | ~configured, axis=-1)
                 & node_valid & configured.any())
        return [name for name, u in zip(node_names, under) if u]

    def balance(self, handle: Handle) -> int:
        under = set(self.underutilized_nodes())
        if len(under) < self.number_of_nodes:
            return 0
        evicted = 0
        for pod in handle.pods():
            if pod.node in under and handle.evict(pod, self.name):
                evicted += 1
        return evicted


#: registry mirroring SetupK8sDeschedulerPlugins (plugin.go:134); the
#: LowNodeUtilization slot is served by LowNodeLoadPlugin over request
#: tensors (same kernels, usage := requested).
PLUGINS = {
    "PodLifeTime": PodLifeTime,
    "RemoveFailedPods": RemoveFailedPods,
    "RemovePodsHavingTooManyRestarts": RemovePodsHavingTooManyRestarts,
    "RemoveDuplicates": RemoveDuplicates,
    "RemovePodsViolatingNodeAffinity": RemovePodsViolatingNodeAffinity,
    "RemovePodsViolatingNodeTaints": RemovePodsViolatingNodeTaints,
    "RemovePodsViolatingInterPodAntiAffinity":
        RemovePodsViolatingInterPodAntiAffinity,
    "RemovePodsViolatingTopologySpreadConstraint":
        RemovePodsViolatingTopologySpreadConstraint,
    "HighNodeUtilization": HighNodeUtilization,
}
