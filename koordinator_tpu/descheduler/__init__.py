"""Descheduling: load rebalancing and arbitrated pod migration.

Mirrors ``pkg/descheduler`` (SURVEY.md section 2.7):

- ``lownodeload`` -- the LowNodeLoad balance plugin as tensor kernels over the
  device-resident cluster state: threshold/deviation classification, victim
  selection bounded by target-node headroom.
- ``migration``   -- the PodMigrationJob controller + arbitrator state machine
  (sort, group limits) on the host, since it is API-protocol-bound.
"""

from koordinator_tpu.descheduler.lownodeload import (
    LowNodeLoadArgs,
    classify_nodes,
    select_victims,
)
from koordinator_tpu.descheduler.migration import (
    MigrationJob,
    MigrationJobPhase,
    MigrationController,
)

__all__ = [
    "LowNodeLoadArgs",
    "classify_nodes",
    "select_victims",
    "MigrationJob",
    "MigrationJobPhase",
    "MigrationController",
]
