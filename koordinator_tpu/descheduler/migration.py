"""PodMigrationJob controller: arbitrated, reservation-backed migration.

Semantics from ``pkg/descheduler/controllers/migration``:

- Jobs are arbitrated before running (arbitrator/arbitrator.go:51): candidates
  are *sorted* (earlier creation first, lower-priority pods first) then
  *filtered* by stability group limits — max concurrent migrations per node /
  namespace / owning workload, and the workload's max-unavailable budget
  (arbitrator/filter.go).
- A reservation for the replacement pod can be requested before eviction
  (migration/reservation/): the job only proceeds to eviction once capacity
  is reserved, so the migrated pod cannot be left homeless.
- Eviction runs through a pluggable evictor (eviction API / delete / soft
  label, migration/evictor/*.go); the job tracks phase + conditions and
  times out.

This is control-plane protocol machinery, so it stays host-side Python; the
expensive part — choosing where replacements go — is delegated to the TPU
solver through the ``reserve_fn`` callback.
"""

from __future__ import annotations

import dataclasses
import enum
import time
from collections import Counter
from typing import Callable, Iterable


class MigrationJobPhase(str, enum.Enum):
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"


@dataclasses.dataclass
class MigrationJob:
    """PodMigrationJob (apis/scheduling/v1alpha1/pod_migration_job_types.go)."""

    name: str
    pod: str
    node: str
    namespace: str = "default"
    workload: str = ""
    priority: int = 0
    create_time: float = dataclasses.field(default_factory=time.monotonic)
    timeout_sec: float = 600.0
    phase: MigrationJobPhase = MigrationJobPhase.PENDING
    reason: str = ""
    reservation: str | None = None
    start_time: float | None = None


@dataclasses.dataclass
class ArbitrationLimits:
    """Group limits (arbitrator/filter.go defaults)."""

    max_migrating_per_node: int = 2
    max_migrating_per_namespace: int = 10
    max_migrating_per_workload: int = 2
    max_unavailable_per_workload: int = 2


class MigrationController:
    """Reconciles MigrationJobs with arbitration and reservation-first flow."""

    def __init__(
        self,
        limits: ArbitrationLimits | None = None,
        reserve_fn: Callable[[MigrationJob], str | None] | None = None,
        evict_fn: Callable[[MigrationJob], bool] | None = None,
        workload_unavailable_fn: Callable[[str], int] | None = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.limits = limits or ArbitrationLimits()
        self.reserve_fn = reserve_fn
        self.evict_fn = evict_fn
        self.workload_unavailable_fn = workload_unavailable_fn
        self.clock = clock
        self.jobs: dict[str, MigrationJob] = {}

    # -- API ---------------------------------------------------------------

    def submit(self, job: MigrationJob) -> None:
        if job.name in self.jobs:
            raise ValueError(f"migration job {job.name!r} already exists")
        self.jobs[job.name] = job

    def running(self) -> list[MigrationJob]:
        return [j for j in self.jobs.values()
                if j.phase is MigrationJobPhase.RUNNING]

    def pending(self) -> list[MigrationJob]:
        return [j for j in self.jobs.values()
                if j.phase is MigrationJobPhase.PENDING]

    # -- arbitration (sort + filter) ---------------------------------------

    def _sorted_candidates(self) -> list[MigrationJob]:
        """arbitrator/sort.go: stable order — older jobs first, lower pod
        priority migrates first (cheaper disruption)."""
        return sorted(self.pending(), key=lambda j: (j.priority, j.create_time))

    def _group_counts(self, jobs: Iterable[MigrationJob]) -> tuple[Counter, Counter, Counter]:
        node, ns, workload = Counter(), Counter(), Counter()
        for j in jobs:
            node[j.node] += 1
            ns[j.namespace] += 1
            if j.workload:
                workload[j.workload] += 1
        return node, ns, workload

    def arbitrate(self) -> list[MigrationJob]:
        """Pick pending jobs allowed to run this round (sort then filter)."""
        node, ns, workload = self._group_counts(self.running())
        allowed: list[MigrationJob] = []
        for job in self._sorted_candidates():
            lim = self.limits
            if node[job.node] >= lim.max_migrating_per_node:
                continue
            if ns[job.namespace] >= lim.max_migrating_per_namespace:
                continue
            if job.workload:
                if workload[job.workload] >= lim.max_migrating_per_workload:
                    continue
                if self.workload_unavailable_fn is not None:
                    unavailable = (self.workload_unavailable_fn(job.workload)
                                   + workload[job.workload])
                    if unavailable >= lim.max_unavailable_per_workload:
                        continue
            allowed.append(job)
            node[job.node] += 1
            ns[job.namespace] += 1
            if job.workload:
                workload[job.workload] += 1
        return allowed

    # -- reconcile ---------------------------------------------------------

    def reconcile(self) -> None:
        """One controller round: arbitrate, reserve, evict, expire."""
        now = self.clock()

        for job in self.arbitrate():
            # reservation-first: secure replacement capacity before evicting
            if self.reserve_fn is not None:
                reservation = self.reserve_fn(job)
                if reservation is None:
                    job.phase = MigrationJobPhase.FAILED
                    job.reason = "ReservationFailed"
                    continue
                job.reservation = reservation
            job.phase = MigrationJobPhase.RUNNING
            job.start_time = now

        for job in self.running():
            if self.evict_fn is not None:
                if self.evict_fn(job):
                    job.phase = MigrationJobPhase.SUCCEEDED
                    job.reason = "Complete"
                    continue
            if job.start_time is not None and now - job.start_time > job.timeout_sec:
                job.phase = MigrationJobPhase.FAILED
                job.reason = "Timeout"

    def gc(self, keep: int = 256) -> None:
        """Drop oldest finished jobs beyond the retention limit."""
        finished = sorted(
            (j for j in self.jobs.values()
             if j.phase in (MigrationJobPhase.SUCCEEDED, MigrationJobPhase.FAILED)),
            key=lambda j: j.create_time,
        )
        for j in finished[:-keep] if len(finished) > keep else []:
            del self.jobs[j.name]
