"""PodMigrationJob controller: arbitrated, reservation-backed migration.

Semantics from ``pkg/descheduler/controllers/migration``:

- Jobs are arbitrated before running (arbitrator/arbitrator.go:51): candidates
  are *sorted* (earlier creation first, lower-priority pods first) then
  *filtered* by stability group limits — max concurrent migrations per node /
  namespace / owning workload, and the workload's max-unavailable budget
  (arbitrator/filter.go).
- A reservation for the replacement pod can be requested before eviction
  (migration/reservation/): the job only proceeds to eviction once capacity
  is reserved, so the migrated pod cannot be left homeless.
- Eviction runs through a pluggable evictor (eviction API / delete / soft
  label, migration/evictor/*.go); the job tracks phase + conditions and
  times out.

This is control-plane protocol machinery, so it stays host-side Python; the
expensive part — choosing where replacements go — is delegated to the TPU
solver through the ``reserve_fn`` callback.
"""

from __future__ import annotations

import dataclasses
import enum
import time
from collections import Counter
from typing import Callable, Iterable


class MigrationJobPhase(str, enum.Enum):
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"


@dataclasses.dataclass
class MigrationJob:
    """PodMigrationJob (apis/scheduling/v1alpha1/pod_migration_job_types.go)."""

    name: str
    pod: str
    node: str
    namespace: str = "default"
    workload: str = ""
    priority: int = 0
    create_time: float = dataclasses.field(default_factory=time.monotonic)
    timeout_sec: float = 600.0
    phase: MigrationJobPhase = MigrationJobPhase.PENDING
    reason: str = ""
    reservation: str | None = None
    start_time: float | None = None


@dataclasses.dataclass
class ArbitrationLimits:
    """Group limits (arbitrator/filter.go defaults). The per-workload specs
    are int-or-percent (e.g. 2 or "10%") resolved against the workload's
    expected replicas via :func:`get_max_unavailable`; None means "use the
    replica-count-dependent default"."""

    max_migrating_per_node: int = 2
    max_migrating_per_namespace: int = 10
    max_migrating_per_workload: int | str | None = None
    max_unavailable_per_workload: int | str | None = None


def scaled_int_or_percent(spec: int | str, replicas: int) -> int:
    """intstr.GetScaledValueFromIntOrPercent, round-down."""
    if isinstance(spec, str):
        if not spec.endswith("%"):
            raise ValueError(f"invalid int-or-percent {spec!r}")
        return replicas * int(spec[:-1]) // 100
    return int(spec)


def get_max_unavailable(replicas: int, spec: int | str | None) -> int:
    """migration/util/util.go:81 GetMaxUnavailable: resolve the spec against
    replicas (a percent that floors to 0 becomes 1); an absent/zero spec
    defaults to 10% above 10 replicas, 2 for 4-10, else 1; capped at
    replicas."""
    max_unavailable = 0
    if spec is not None:
        max_unavailable = scaled_int_or_percent(spec, replicas)
        if max_unavailable == 0:
            max_unavailable = 1  # a percent flooring to 0 still allows one
    if max_unavailable == 0:
        if replicas > 10:
            max_unavailable = replicas * 10 // 100
        elif 4 <= replicas <= 10:
            max_unavailable = 2
        else:
            max_unavailable = 1
    return min(max_unavailable, replicas)


def get_max_migrating(replicas: int, spec: int | str | None) -> int:
    """migration/util/util.go:116 — same resolution as max-unavailable."""
    return get_max_unavailable(replicas, spec)


@dataclasses.dataclass
class Workload:
    """What the controllerfinder resolves for an owner ref
    (pkg/util/controllerfinder: GetPodsForRef → expected replicas; the
    workload's own rollout maxUnavailable when it declares one)."""

    ref: str                               # "Kind/name"
    expected_replicas: int
    max_unavailable: int | str | None = None   # workload spec override
    unavailable: int = 0                   # currently not-ready pods


class ControllerFinder:
    """Resolves a pod's owning workload to (replicas, budgets) — the
    reference's controllerfinder seam, fed by the states informer here."""

    def __init__(self) -> None:
        self._workloads: dict[str, Workload] = {}

    def register(self, workload: Workload) -> None:
        self._workloads[workload.ref] = workload

    def get(self, ref: str) -> Workload | None:
        return self._workloads.get(ref)


class MigrationController:
    """Reconciles MigrationJobs with arbitration and reservation-first flow."""

    def __init__(
        self,
        limits: ArbitrationLimits | None = None,
        reserve_fn: Callable[[MigrationJob], str | None] | None = None,
        evict_fn: Callable[[MigrationJob], bool] | None = None,
        workload_unavailable_fn: Callable[[str], int] | None = None,
        controller_finder: ControllerFinder | None = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.limits = limits or ArbitrationLimits()
        self.reserve_fn = reserve_fn
        self.evict_fn = evict_fn
        self.workload_unavailable_fn = workload_unavailable_fn
        self.controller_finder = controller_finder
        self.clock = clock
        self.jobs: dict[str, MigrationJob] = {}

    def _workload_budgets(self, ref: str) -> tuple[int, int, int]:
        """(max_migrating, max_unavailable, already_unavailable) for the
        owning workload — replica-scaled when the controllerfinder knows it
        (filter.go:409 filterMaxMigratingOrUnavailablePerWorkload), flat
        config values otherwise."""
        lim = self.limits
        workload = (self.controller_finder.get(ref)
                    if self.controller_finder else None)
        if workload is not None:
            replicas = workload.expected_replicas
            max_migrating = get_max_migrating(
                replicas, lim.max_migrating_per_workload)
            spec = (workload.max_unavailable
                    if workload.max_unavailable is not None
                    else lim.max_unavailable_per_workload)
            max_unavailable = get_max_unavailable(replicas, spec)
            unavailable = workload.unavailable
        else:
            def flat(spec, default=2):
                return spec if isinstance(spec, int) and spec > 0 else default
            max_migrating = flat(lim.max_migrating_per_workload)
            max_unavailable = flat(lim.max_unavailable_per_workload)
            unavailable = 0
        if self.workload_unavailable_fn is not None:
            unavailable = self.workload_unavailable_fn(ref)
        return max_migrating, max_unavailable, unavailable

    # -- API ---------------------------------------------------------------

    def submit(self, job: MigrationJob) -> None:
        if job.name in self.jobs:
            raise ValueError(f"migration job {job.name!r} already exists")
        self.jobs[job.name] = job

    def running(self) -> list[MigrationJob]:
        return [j for j in self.jobs.values()
                if j.phase is MigrationJobPhase.RUNNING]

    def pending(self) -> list[MigrationJob]:
        return [j for j in self.jobs.values()
                if j.phase is MigrationJobPhase.PENDING]

    # -- arbitration (sort + filter) ---------------------------------------

    def _sorted_candidates(self) -> list[MigrationJob]:
        """arbitrator/sort.go: stable order — older jobs first, lower pod
        priority migrates first (cheaper disruption)."""
        return sorted(self.pending(), key=lambda j: (j.priority, j.create_time))

    def _group_counts(self, jobs: Iterable[MigrationJob]) -> tuple[Counter, Counter, Counter]:
        node, ns, workload = Counter(), Counter(), Counter()
        for j in jobs:
            node[j.node] += 1
            ns[j.namespace] += 1
            if j.workload:
                workload[j.workload] += 1
        return node, ns, workload

    def arbitrate(self) -> list[MigrationJob]:
        """Pick pending jobs allowed to run this round (sort then filter)."""
        node, ns, workload = self._group_counts(self.running())
        allowed: list[MigrationJob] = []
        for job in self._sorted_candidates():
            lim = self.limits
            if node[job.node] >= lim.max_migrating_per_node:
                continue
            if ns[job.namespace] >= lim.max_migrating_per_namespace:
                continue
            if job.workload:
                max_migrating, max_unavailable, already_unavailable = (
                    self._workload_budgets(job.workload))
                if workload[job.workload] >= max_migrating:
                    continue
                # migrating pods count as unavailable (filter.go:484
                # mergeUnavailableAndMigratingPods)
                if (already_unavailable + workload[job.workload]
                        >= max_unavailable):
                    continue
            allowed.append(job)
            node[job.node] += 1
            ns[job.namespace] += 1
            if job.workload:
                workload[job.workload] += 1
        return allowed

    # -- reconcile ---------------------------------------------------------

    def reconcile(self) -> None:
        """One controller round: arbitrate, reserve, evict, expire."""
        now = self.clock()

        for job in self.arbitrate():
            # reservation-first: secure replacement capacity before evicting
            if self.reserve_fn is not None:
                reservation = self.reserve_fn(job)
                if reservation is None:
                    job.phase = MigrationJobPhase.FAILED
                    job.reason = "ReservationFailed"
                    continue
                job.reservation = reservation
            job.phase = MigrationJobPhase.RUNNING
            job.start_time = now

        for job in self.running():
            if self.evict_fn is not None:
                if self.evict_fn(job):
                    job.phase = MigrationJobPhase.SUCCEEDED
                    job.reason = "Complete"
                    continue
            if job.start_time is not None and now - job.start_time > job.timeout_sec:
                job.phase = MigrationJobPhase.FAILED
                job.reason = "Timeout"

        from koordinator_tpu import metrics

        counts = {phase: 0 for phase in MigrationJobPhase}
        for job in self.jobs.values():
            counts[job.phase] += 1
        for phase, n in counts.items():
            metrics.migration_jobs.set(
                float(n), labels={"phase": phase.value})

    def gc(self, keep: int = 256) -> None:
        """Drop oldest finished jobs beyond the retention limit."""
        finished = sorted(
            (j for j in self.jobs.values()
             if j.phase in (MigrationJobPhase.SUCCEEDED, MigrationJobPhase.FAILED)),
            key=lambda j: j.create_time,
        )
        for j in finished[:-keep] if len(finished) > keep else []:
            del self.jobs[j.name]
