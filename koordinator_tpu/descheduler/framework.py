"""Descheduler plugin framework (reference: ``pkg/descheduler/framework/
types.go:78-98`` — DeschedulePlugin / BalancePlugin / EvictPlugin /
FilterPlugin; profiles ``profile/``; runtime registry ``framework/runtime/``;
eviction plumbing with PDB respect ``evictions/``; evictor modes
``controllers/migration/evictor/``).

A profile bundles plugins; the descheduler loop runs every profile's
Deschedule then Balance plugins each interval. Evictions flow through the
:class:`EvictorFilter` (PDB budgets, priority threshold, owner-kind guards)
and then one of the evictor modes (eviction API / delete / soft label —
represented by pluggable sinks).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Optional, Protocol

from koordinator_tpu.api import extension as ext


@dataclasses.dataclass(frozen=True)
class PodInfo:
    """Descheduler-side pod view."""

    uid: str
    name: str
    namespace: str
    node: str
    priority: int = 0
    qos_class: str = "NONE"
    owner: str = ""                  # workload ref "Kind/name"
    labels: dict = dataclasses.field(default_factory=dict)
    annotations: dict = dataclasses.field(default_factory=dict)
    is_daemonset: bool = False
    has_local_storage: bool = False
    # fields consumed by the upstream-port plugins (descheduler/upstream.py)
    created: float = 0.0                 # creation timestamp (epoch seconds)
    phase: str = "Running"               # Pending/Running/Succeeded/Failed
    reason: str = ""                     # status.reason (e.g. OOMKilled)
    restart_count: int = 0
    images: tuple = ()                   # container image names
    node_selector: dict = dataclasses.field(default_factory=dict)
    # required node affinity: list of terms; a term is a tuple of
    # (key, op, values) expressions, op in {In, NotIn, Exists, DoesNotExist};
    # the pod fits a node if ANY term has ALL expressions matching
    required_affinity: tuple = ()
    # tolerations: (key, operator, value, effect); operator Equal/Exists,
    # empty key + Exists tolerates everything, empty effect matches all
    tolerations: tuple = ()
    # anti-affinity terms owned by THIS pod: (selector dict, topology_key)
    anti_affinity: tuple = ()
    # topology spread constraints: (topology_key, max_skew, selector dict)
    spread_constraints: tuple = ()


@dataclasses.dataclass
class PDB:
    """PodDisruptionBudget relevant state."""

    selector: dict
    disruptions_allowed: int


class Handle(Protocol):
    """What plugins get (framework/types.go Handle): state + evictor."""

    def pods(self) -> list[PodInfo]: ...

    def evict(self, pod: PodInfo, reason: str) -> bool: ...


class DeschedulePlugin(Protocol):
    name: str

    def deschedule(self, handle: Handle) -> int: ...


class BalancePlugin(Protocol):
    name: str

    def balance(self, handle: Handle) -> int: ...


class EvictorFilter:
    """defaultevictor semantics: which pods may be evicted at all."""

    def __init__(
        self,
        evict_system_critical: bool = False,
        evict_local_storage: bool = False,
        evict_daemonsets: bool = False,
        priority_threshold: Optional[int] = None,
        pdbs: Optional[list[PDB]] = None,
        extra_filters: Optional[list[Callable[[PodInfo], bool]]] = None,
    ):
        self.evict_system_critical = evict_system_critical
        self.evict_local_storage = evict_local_storage
        self.evict_daemonsets = evict_daemonsets
        self.priority_threshold = priority_threshold
        self.pdbs = list(pdbs or [])
        self.extra_filters = list(extra_filters or [])

    def _pdb_for(self, pod: PodInfo) -> Optional[PDB]:
        for pdb in self.pdbs:
            if all(pod.labels.get(k) == v for k, v in pdb.selector.items()):
                return pdb
        return None

    def filter(self, pod: PodInfo) -> tuple[bool, str]:
        """(evictable, reason-if-not)."""
        if pod.is_daemonset and not self.evict_daemonsets:
            return False, "daemonset pod"
        if pod.has_local_storage and not self.evict_local_storage:
            return False, "pod has local storage"
        if (not self.evict_system_critical
                and pod.priority >= 2_000_000_000):
            return False, "system critical priority"
        if (self.priority_threshold is not None
                and pod.priority >= self.priority_threshold):
            return False, "priority above threshold"
        if pod.annotations.get(ext.ANNOTATION_EVICTION_COST, "") == "-2147483648":
            return False, "eviction cost forbids"
        pdb = self._pdb_for(pod)
        if pdb is not None and pdb.disruptions_allowed <= 0:
            return False, "PDB exhausted"
        for fn in self.extra_filters:
            if not fn(pod):
                return False, "plugin filter"
        return True, ""

    def consume_budget(self, pod: PodInfo) -> None:
        pdb = self._pdb_for(pod)
        if pdb is not None:
            pdb.disruptions_allowed -= 1


# ---- evictor modes (migration/evictor/*.go) --------------------------------

MODE_EVICT = "Eviction"        # eviction API (PDB-checked server-side too)
MODE_DELETE = "Delete"         # direct delete
MODE_SOFT = "SoftMigrate"      # annotate only; an external system drains


class Evictor:
    """Eviction executor with pluggable transport per mode."""

    def __init__(self, mode: str = MODE_EVICT,
                 evict_fn: Optional[Callable[[PodInfo], bool]] = None,
                 delete_fn: Optional[Callable[[PodInfo], bool]] = None,
                 label_fn: Optional[Callable[[PodInfo, dict], bool]] = None):
        self.mode = mode
        self.evict_fn = evict_fn
        self.delete_fn = delete_fn
        self.label_fn = label_fn
        self.evicted: list[tuple[str, str]] = []
        self.profile = ""   # stamped by ProfileRunner for metric attribution

    def evict(self, pod: PodInfo, reason: str) -> bool:
        ok = False
        if self.mode == MODE_EVICT:
            ok = self.evict_fn(pod) if self.evict_fn else True
        elif self.mode == MODE_DELETE:
            ok = self.delete_fn(pod) if self.delete_fn else True
        elif self.mode == MODE_SOFT:
            labels = {ext.LABEL_SOFT_EVICTION: reason}
            ok = self.label_fn(pod, labels) if self.label_fn else True
        if ok:
            from koordinator_tpu.metrics import descheduler_evictions_total

            descheduler_evictions_total.inc(
                labels={"profile": self.profile, "reason": reason})
            self.evicted.append((pod.uid, reason))
        return ok


@dataclasses.dataclass
class Profile:
    """One descheduling profile (profile/profile.go)."""

    name: str
    deschedule_plugins: list = dataclasses.field(default_factory=list)
    balance_plugins: list = dataclasses.field(default_factory=list)
    evictor_filter: EvictorFilter = dataclasses.field(default_factory=EvictorFilter)
    evictor: Evictor = dataclasses.field(default_factory=Evictor)
    max_evictions_per_round: int = 0   # 0 = unlimited


class _ProfileHandle:
    def __init__(self, profile: Profile, pods_fn: Callable[[], list[PodInfo]]):
        self.profile = profile
        profile.evictor.profile = profile.name
        self._pods_fn = pods_fn
        self.evictions = 0
        #: uids evicted this round — overlapping plugins (a Failed pod can
        #: match RemoveFailedPods AND PodLifeTime) must not double-evict,
        #: double-decrement PDB budgets, or double-count the round cap
        self._evicted_uids: set[str] = set()

    def pods(self) -> list[PodInfo]:
        return self._pods_fn()

    def evict(self, pod: PodInfo, reason: str) -> bool:
        if pod.uid in self._evicted_uids:
            return False
        limit = self.profile.max_evictions_per_round
        if limit and self.evictions >= limit:
            return False
        ok, _ = self.profile.evictor_filter.filter(pod)
        if not ok:
            return False
        if not self.profile.evictor.evict(pod, reason):
            return False
        self.profile.evictor_filter.consume_budget(pod)
        self.evictions += 1
        self._evicted_uids.add(pod.uid)
        return True


class Descheduler:
    """The loop (pkg/descheduler/descheduler.go): every interval, run each
    profile's Deschedule plugins then Balance plugins."""

    def __init__(self, profiles: list[Profile],
                 pods_fn: Callable[[], list[PodInfo]],
                 interval_seconds: float = 120.0, clock=time.time,
                 elector=None):
        self.profiles = profiles
        self.pods_fn = pods_fn
        self.interval_seconds = interval_seconds
        self.clock = clock
        #: optional ha.LeaderElector — the reference leader-elects the
        #: descheduler binary; a non-leader replica ticks but never evicts
        self.elector = elector
        self._last_run = 0.0

    def run_once(self) -> dict[str, int]:
        """One descheduling round; returns evictions per profile."""
        out = {}
        for profile in self.profiles:
            handle = _ProfileHandle(profile, self.pods_fn)
            for plugin in profile.deschedule_plugins:
                plugin.deschedule(handle)
            for plugin in profile.balance_plugins:
                plugin.balance(handle)
            out[profile.name] = handle.evictions
        return out

    def tick(self) -> Optional[dict[str, int]]:
        if self.elector is not None and not self.elector.tick():
            return None
        now = self.clock()
        if now - self._last_run < self.interval_seconds:
            return None
        self._last_run = now
        return self.run_once()
