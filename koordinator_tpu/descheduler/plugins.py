"""Descheduler plugins over the framework (reference:
``pkg/descheduler/framework/plugins/``): LowNodeLoad balance bridging the
tensor kernels, custom-priority deschedule, and the migration-controller
evict sink.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp
import numpy as np

from koordinator_tpu.descheduler import lownodeload as lnl
from koordinator_tpu.descheduler.framework import Handle, PodInfo
from koordinator_tpu.descheduler.migration import MigrationController, MigrationJob


class LowNodeLoadPlugin:
    """Balance plugin: classify by real utilization, evict from anomalous hot
    nodes into the cold pool's head-room — all selection math on-device
    (lownodeload kernels), eviction through the profile's filter+evictor.

    ``state_fn`` returns (usage(N,R), capacity(N,R), node_valid(N,),
    node_names[N]); ``pod_usage_fn(pod)`` a (R,) usage vector.
    """

    name = "LowNodeLoad"

    def __init__(
        self,
        state_fn: Callable[[], tuple[np.ndarray, np.ndarray, np.ndarray, list[str]]],
        pod_usage_fn: Callable[[PodInfo], np.ndarray],
        args: Optional[lnl.LowNodeLoadArgs] = None,
    ):
        self.state_fn = state_fn
        self.pod_usage_fn = pod_usage_fn
        self.args = args or lnl.LowNodeLoadArgs.default()
        self._anomaly = None  # (N,) counters, lazily sized

    def balance(self, handle: Handle) -> int:
        usage, capacity, node_valid, node_names = self.state_fn()
        n = usage.shape[0]
        if self._anomaly is None or self._anomaly.shape[0] != n:
            self._anomaly = jnp.zeros(n, jnp.int32)
        node_index = {name: i for i, name in enumerate(node_names)}

        pods = [p for p in handle.pods() if p.node in node_index]
        pod_node = np.asarray(
            [node_index[p.node] for p in pods] or [0], np.int32
        )
        pod_usage = np.stack(
            [self.pod_usage_fn(p) for p in pods]
        ) if pods else np.zeros((1, usage.shape[1]), np.int32)
        pod_priority = np.asarray([p.priority for p in pods] or [0], np.int32)
        # host-side eviction filters feed the kernel's evictable mask
        from koordinator_tpu.descheduler.framework import _ProfileHandle

        if isinstance(handle, _ProfileHandle):
            evictable = np.asarray(
                [handle.profile.evictor_filter.filter(p)[0] for p in pods]
                or [False]
            )
        else:
            evictable = np.ones(max(len(pods), 1), bool)

        _, over = lnl.classify_nodes(
            jnp.asarray(usage), jnp.asarray(capacity), jnp.asarray(node_valid),
            self.args,
        )
        self._anomaly = lnl.update_anomaly_counters(self._anomaly, over)
        victims = np.asarray(lnl.select_victims(
            jnp.asarray(usage), jnp.asarray(capacity), jnp.asarray(node_valid),
            jnp.asarray(pod_node), jnp.asarray(pod_usage),
            jnp.asarray(pod_priority), jnp.asarray(evictable),
            self._anomaly, self.args,
        ))
        evicted = 0
        for pod, is_victim in zip(pods, victims):
            if is_victim and handle.evict(pod, "LowNodeLoad"):
                evicted += 1
        return evicted


class FragmentationAwarePlugin:
    """Balance plugin (plugins/fragmentationaware): evict the pods whose
    removal most reduces per-node resource-fraction stddev. Scoring and
    greedy selection run on-device (fragmentationaware kernels).

    ``state_fn`` returns (requested(N,R), allocatable(N,R), node_valid(N,),
    node_names[N]); ``pod_requests_fn(pod)`` a (R,) milli-unit vector.
    """

    name = "FragmentationAware"

    def __init__(
        self,
        state_fn: Callable[[], tuple[np.ndarray, np.ndarray, np.ndarray, list[str]]],
        pod_requests_fn: Callable[[PodInfo], np.ndarray],
        resource_mask: Optional[np.ndarray] = None,
        imbalance_threshold: float = 0.2,
        min_gain: float = 0.05,
        max_victims: int = 16,
    ):
        self.state_fn = state_fn
        self.pod_requests_fn = pod_requests_fn
        self.resource_mask = resource_mask
        self.imbalance_threshold = imbalance_threshold
        self.min_gain = min_gain
        self.max_victims = max_victims

    def balance(self, handle: Handle) -> int:
        from koordinator_tpu.descheduler import fragmentationaware as frag
        from koordinator_tpu.descheduler.framework import _ProfileHandle

        requested, allocatable, node_valid, node_names = self.state_fn()
        node_index = {name: i for i, name in enumerate(node_names)}
        pods = [p for p in handle.pods() if p.node in node_index]
        if not pods:
            return 0
        pod_node = np.asarray([node_index[p.node] for p in pods], np.int32)
        pod_requests = np.stack([self.pod_requests_fn(p) for p in pods])
        if isinstance(handle, _ProfileHandle):
            evictable = np.asarray(
                [handle.profile.evictor_filter.filter(p)[0] for p in pods]
            )
        else:
            evictable = np.ones(len(pods), bool)
        mask = (jnp.asarray(self.resource_mask)
                if self.resource_mask is not None
                else frag.default_resource_mask())

        victims = np.asarray(frag.select_victims(
            jnp.asarray(requested), jnp.asarray(allocatable),
            jnp.asarray(node_valid), jnp.asarray(pod_node),
            jnp.asarray(pod_requests), jnp.asarray(evictable), mask,
            imbalance_threshold=self.imbalance_threshold,
            min_gain=self.min_gain, max_victims=self.max_victims,
        ))
        evicted = 0
        for pod, is_victim in zip(pods, victims):
            if is_victim and handle.evict(pod, "FragmentationAware"):
                evicted += 1
        return evicted


class CustomPriorityPlugin:
    """Deschedule plugin (plugins/custompriority): evict pods below a
    priority floor from matching nodes (cleanup of stale low-priority work)."""

    name = "CustomPriority"

    def __init__(self, priority_floor: int,
                 node_filter: Optional[Callable[[str], bool]] = None):
        self.priority_floor = priority_floor
        self.node_filter = node_filter

    def deschedule(self, handle: Handle) -> int:
        evicted = 0
        for pod in handle.pods():
            if pod.priority >= self.priority_floor:
                continue
            if self.node_filter and not self.node_filter(pod.node):
                continue
            if handle.evict(pod, "CustomPriority"):
                evicted += 1
        return evicted


def migration_evict_fn(controller: MigrationController,
                       clock=None) -> Callable[[PodInfo], bool]:
    """Evict sink that creates PodMigrationJobs instead of direct eviction —
    the reference's 'evictor plugin = migration controller' wiring
    (SURVEY.md 3.4)."""
    counter = [0]

    def evict(pod: PodInfo) -> bool:
        counter[0] += 1
        job = MigrationJob(
            name=f"migrate-{pod.uid}-{counter[0]}",
            pod=pod.uid, node=pod.node, namespace=pod.namespace,
            workload=pod.owner, priority=pod.priority,
        )
        try:
            controller.submit(job)
        except ValueError:
            return False
        return True

    return evict


def scheduler_reserve_fn(
    scheduler, ttl_sec: float = 1800.0
) -> Callable[[MigrationJob], str | None]:
    """Reservation-first arbitration against the in-process scheduler
    (migration/reservation.go: secure replacement capacity BEFORE evicting):
    create a Reservation sized to the migrating pod and owned by its labels
    or workload, run a round to place it, and hand the name to the job.
    Placement back on the source node is rejected — a migration must move
    the pod — and a failed placement cleans the reservation up.

    The reservation is allocate-once (it backs exactly one replacement pod;
    its charge then lives and dies with that pod) with a TTL so a
    replacement that never arrives can't hide capacity forever."""
    from koordinator_tpu.scheduler.reservations import (
        OwnerMatcher,
        ReservationPhase,
        ReservationSpec,
    )

    def reserve(job: MigrationJob) -> str | None:
        bound = scheduler.bound.get(job.pod)
        if bound is None:
            return None
        owners = ([OwnerMatcher(labels=dict(bound.labels))]
                  if bound.labels else [])
        if not owners and job.workload:
            owners = [OwnerMatcher(controller=job.workload)]
        if not owners:
            return None
        name = f"migrate-{job.name}"
        scheduler.add_reservation(ReservationSpec(
            name=name, requests=np.asarray(bound.requests), owners=owners,
            allocate_once=True, ttl_sec=ttl_sec))
        scheduler.schedule_round()
        spec = scheduler.reservations.get(name)
        if (spec is not None
                and spec.phase is ReservationPhase.AVAILABLE
                and spec.node != bound.node):
            return name
        scheduler.remove_reservation(name)
        return None

    return reserve


def scheduler_migration_evict_fn(scheduler) -> Callable[[MigrationJob], bool]:
    """evict_fn for :class:`MigrationController` against the in-process
    scheduler: the bound pod releases its capacity (and quota) the way an
    informer pod-delete would."""

    def evict(job: MigrationJob) -> bool:
        scheduler.delete_pod(job.pod)
        return True

    return evict
