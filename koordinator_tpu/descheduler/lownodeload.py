"""LowNodeLoad: classify nodes by real utilization, pick eviction victims.

Semantics from ``pkg/descheduler/framework/plugins/loadaware``:

- classifyNodes (utilization_util.go:239): a node is *underutilized* when
  every configured resource sits below its low threshold, *overutilized* when
  any resource exceeds its high threshold (thresholds are percentages of node
  capacity; NodeMetric usage, not requests).
- deviation thresholds (low_node_load.go:314 newThresholds with
  UseDeviationThresholds): low/high become mean(usage%) -/+ the configured
  deviation, clamped to [0, 100].
- victim selection (utilization_util.go:308 evictPodsFromSourceNodes): the
  budget is the sum over underutilized nodes of (high-threshold capacity -
  usage); pods move off overutilized nodes — sorted cheapest-first — only
  while their node stays above the high threshold and budget remains.
- anomaly gating (low_node_load.go:286 filterRealAbnormalNodes): a node must
  be observed overutilized in several consecutive rounds before eviction;
  tracked here as a per-node counter tensor.

All kernels take the (N, R) usage/capacity tensors already resident for
scheduling — the descheduler reads the same cluster state (BASELINE.json north
star).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS


@struct.dataclass
class LowNodeLoadArgs:
    """LowNodeLoadArgs (descheduler apis/config): thresholds are int32
    percentages; -1 = resource not configured."""

    low_thresholds: jax.Array   # (R,) int32
    high_thresholds: jax.Array  # (R,) int32
    use_deviation: jax.Array    # () bool
    anomaly_rounds: jax.Array   # () int32 — consecutive rounds before evicting

    @classmethod
    def default(cls) -> "LowNodeLoadArgs":
        from koordinator_tpu.api.resources import ResourceDim

        low = jnp.full(NUM_RESOURCE_DIMS, -1, jnp.int32)
        high = jnp.full(NUM_RESOURCE_DIMS, -1, jnp.int32)
        low = low.at[ResourceDim.CPU].set(45).at[ResourceDim.MEMORY].set(60)
        high = high.at[ResourceDim.CPU].set(65).at[ResourceDim.MEMORY].set(80)
        return cls(
            low_thresholds=low,
            high_thresholds=high,
            use_deviation=jnp.asarray(False),
            anomaly_rounds=jnp.int32(3),
        )


def usage_percent(usage: jnp.ndarray, capacity: jnp.ndarray) -> jnp.ndarray:
    """(N, R) usage percentage of capacity; 0 where capacity is 0."""
    return jnp.where(capacity > 0, usage * 100 // jnp.maximum(capacity, 1), 0)


def effective_thresholds(
    args: LowNodeLoadArgs,
    usage_pct: jnp.ndarray,   # (N, R)
    node_valid: jnp.ndarray,  # (N,)
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(low, high) per resource; deviation mode recenters on the pool mean."""
    configured = args.low_thresholds >= 0
    n = jnp.maximum(jnp.sum(node_valid), 1)
    mean = jnp.sum(jnp.where(node_valid[:, None], usage_pct, 0), axis=0) // n
    dev_low = jnp.clip(mean - jnp.maximum(args.low_thresholds, 0), 0, 100)
    dev_high = jnp.clip(mean + jnp.maximum(args.high_thresholds, 0), 0, 100)
    low = jnp.where(args.use_deviation, dev_low, args.low_thresholds)
    high = jnp.where(args.use_deviation, dev_high, args.high_thresholds)
    return (
        jnp.where(configured, low, -1),
        jnp.where(configured, high, -1),
    )


def _classify(pct, low, high, node_valid):
    configured = low >= 0
    under = jnp.all((pct < low) | ~configured, axis=-1) & node_valid
    over = jnp.any(configured & (pct > high), axis=-1) & node_valid
    return under, over


def _high_quantity(capacity, high, unconfigured_fill):
    """capacity * high% for configured dims; fill elsewhere."""
    return jnp.where(high >= 0, capacity * jnp.maximum(high, 0) // 100,
                     unconfigured_fill)


def classify_nodes(
    usage: jnp.ndarray,      # (N, R)
    capacity: jnp.ndarray,   # (N, R)
    node_valid: jnp.ndarray, # (N,)
    args: LowNodeLoadArgs,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(underutilized, overutilized) boolean masks, each (N,)."""
    pct = usage_percent(usage, capacity)
    low, high = effective_thresholds(args, pct, node_valid)
    return _classify(pct, low, high, node_valid)


def update_anomaly_counters(
    counters: jnp.ndarray,  # (N,) int32 consecutive-overutilized rounds
    over: jnp.ndarray,      # (N,) bool this round
) -> jnp.ndarray:
    """filterRealAbnormalNodes counter: increment while over, reset when not."""
    return jnp.where(over, counters + 1, 0)


def eviction_budget(
    usage: jnp.ndarray,
    capacity: jnp.ndarray,
    under: jnp.ndarray,
    high: jnp.ndarray,
) -> jnp.ndarray:
    """(R,) total head-room on underutilized nodes:
    sum(high% * capacity - usage), clamped at 0 per node
    (targetAvailableUsage, utilization_util.go:468)."""
    high_quant = _high_quantity(capacity, high, 0)
    room = jnp.maximum(high_quant - usage, 0)
    return jnp.sum(jnp.where(under[:, None] & (high >= 0), room, 0), axis=0)


def select_victims(
    usage: jnp.ndarray,        # (N, R) node usage
    capacity: jnp.ndarray,     # (N, R)
    node_valid: jnp.ndarray,   # (N,)
    pod_node: jnp.ndarray,     # (P,) int32 — node each pod runs on, -1 none
    pod_usage: jnp.ndarray,    # (P, R) — per-pod usage
    pod_priority: jnp.ndarray, # (P,) int32
    pod_evictable: jnp.ndarray,# (P,) bool — passed the eviction filters (PDB,
                               #   owner kind, QoS policy...) computed host-side
    anomaly_counters: jnp.ndarray,  # (N,) int32
    args: LowNodeLoadArgs,
) -> jnp.ndarray:
    """(P,) bool victim mask.

    Evicts lowest-priority pods first from anomalous overutilized nodes, while
    (a) the node remains above its high threshold and (b) the underutilized
    pool still has head-room for the pod (balancePods/evictPods semantics).
    """
    pct = usage_percent(usage, capacity)
    low, high = effective_thresholds(args, pct, node_valid)
    under, over = _classify(pct, low, high, node_valid)
    abnormal = over & (anomaly_counters >= args.anomaly_rounds)
    budget = eviction_budget(usage, capacity, under, high)

    high_quant = _high_quantity(capacity, high, jnp.int32(2**30))

    # cheapest (lowest priority, then smallest cpu usage) pods first
    p = pod_node.shape[0]
    order = jnp.lexsort((pod_usage[:, 0], pod_priority))

    def step(carry, idx):
        node_usage, budget = carry
        node = pod_node[idx]
        safe = jnp.maximum(node, 0)
        candidate = (
            (node >= 0)
            & pod_evictable[idx]
            & abnormal[safe]
            # node still above high threshold on some configured dim
            & jnp.any((high >= 0) & (node_usage[safe] > high_quant[safe]))
            # pool head-room covers this pod on every configured dim
            & jnp.all((high < 0) | (pod_usage[idx] <= budget))
        )
        delta = jnp.where(candidate, pod_usage[idx], 0)
        node_usage = node_usage.at[safe].add(-delta)
        budget = budget - delta
        return (node_usage, budget), candidate

    (_, _), victims_in_order = jax.lax.scan(step, (usage, budget), order)
    victims = jnp.zeros(p, bool).at[order].set(victims_in_order)
    return victims
