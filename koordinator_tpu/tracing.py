"""Dependency-free distributed tracing for the control plane.

The reference koordinator debugs a placement by reading five binaries'
logs; this module gives the rebuild one artifact instead: a trace.  A
``TraceContext`` (trace_id + span_id) rides RPC frame documents and
deltasync event entries exactly the way ``deadline_ms`` does — as a
plain JSON field (``TRACE_DOC_KEY``) — so a pod enqueued in one process
and reconciled in another leaves spans that share one trace_id.

Pieces:

- :class:`Span`: trace_id / span_id / parent_id, service, attributes,
  timestamped events, status.  Wall-clock start plus a perf-counter
  duration (cross-process ordering uses the wall clock; intra-span
  precision uses the monotonic one).
- :class:`Tracer`: thread-local context stack.  ``span(...)`` opens a
  child of the current span (or of an explicitly ``parent=``-ed remote
  context); ``activate(ctx)`` installs a REMOTE parent for a block —
  the server-side half of wire propagation.  Finished spans fan out to
  pluggable exporters and into a bounded ring the debug endpoints read
  (``/debug/trace/<pod>``).
- Exporters: :class:`InMemoryExporter` (tests), :class:`JsonlExporter`
  (soaks/ops; one JSON object per line, crash-safe appends).  Setting
  ``KOORD_TRACE_JSONL=<path>`` in the environment wires a JSONL
  exporter at import time, so any binary can be told to record without
  code changes (``tools/soak.sh`` SOAK_TRACE=1 uses this; pretty-print
  with ``tools/trace_dump.py``).

Everything is O(1) locks + dict ops; no sampling machinery, no
background threads.  Hot paths create spans only when a trace is
actually in flight (propagated context or an opt-in), so an untraced
50k-pod round pays one round span, not 50k.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import threading
import time
from collections import deque
from typing import Callable, Iterator, Mapping, Optional

#: field name a TraceContext rides under in RPC frame docs and deltasync
#: event entries (the ``deadline_ms`` pattern: plain JSON, schema-extra)
TRACE_DOC_KEY = "trace"

#: pod annotation key carrying a trace context between binaries that
#: talk through pod objects (scheduler bind -> kubelet -> koordlet
#: reconcile), the role patched annotations play in the reference
TRACE_ANNOTATION = "koordinator.sh/trace-context"


def _new_trace_id() -> str:
    return os.urandom(16).hex()


def _new_span_id() -> str:
    return os.urandom(8).hex()


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """The propagated identity: which trace, and which span to parent."""

    trace_id: str
    span_id: str

    def to_doc(self) -> dict:
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    @staticmethod
    def from_doc(doc) -> Optional["TraceContext"]:
        """Lenient decode: wire peers may send garbage; a malformed
        context drops silently (tracing must never fail a request)."""
        if not isinstance(doc, dict):
            return None
        tid, sid = doc.get("trace_id"), doc.get("span_id")
        if not (isinstance(tid, str) and tid
                and isinstance(sid, str) and sid):
            return None
        return TraceContext(trace_id=tid, span_id=sid)

    def to_annotation(self) -> str:
        return json.dumps(self.to_doc(), separators=(",", ":"))

    @staticmethod
    def from_annotation(value) -> Optional["TraceContext"]:
        if not isinstance(value, str) or not value:
            return None
        try:
            return TraceContext.from_doc(json.loads(value))
        except (ValueError, TypeError):
            return None


class Span:
    """One timed operation.  Mutate only between start and end()."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "service",
                 "start_time", "_start_perf", "duration_s", "attributes",
                 "events", "status", "_tracer")

    def __init__(self, name: str, trace_id: str, span_id: str,
                 parent_id: Optional[str], service: str,
                 start_time: float, start_perf: float,
                 attributes: Optional[dict] = None, tracer=None):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.service = service
        self.start_time = start_time
        self._start_perf = start_perf
        self.duration_s: Optional[float] = None
        self.attributes: dict = dict(attributes or {})
        self.events: list[dict] = []
        self.status = "ok"
        self._tracer = tracer

    def context(self) -> TraceContext:
        return TraceContext(trace_id=self.trace_id, span_id=self.span_id)

    def set_attribute(self, key: str, value) -> None:
        self.attributes[key] = value

    def set_attributes(self, attrs: Mapping) -> None:
        self.attributes.update(attrs)

    def add_event(self, name: str,
                  attributes: Optional[Mapping] = None) -> None:
        self.events.append({
            "name": name,
            "time": time.time(),
            **({"attributes": dict(attributes)} if attributes else {}),
        })

    def set_error(self, message: str) -> None:
        self.status = "error"
        self.attributes.setdefault("error", message)

    def end(self) -> None:
        """Idempotent; finishes the span and exports it."""
        if self.duration_s is not None:
            return
        self.duration_s = max(0.0, time.perf_counter() - self._start_perf)
        if self._tracer is not None:
            self._tracer._export(self)

    def to_doc(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "service": self.service,
            "start_time": self.start_time,
            "duration_s": self.duration_s,
            "status": self.status,
            "attributes": self.attributes,
            "events": self.events,
        }


class InMemoryExporter:
    """Collects finished spans (tests, interactive debugging)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.spans: list[Span] = []

    def export(self, span: Span) -> None:
        with self._lock:
            self.spans.append(span)

    def by_trace(self, trace_id: str) -> list[Span]:
        with self._lock:
            return [s for s in self.spans if s.trace_id == trace_id]

    def find(self, name: Optional[str] = None,
             service: Optional[str] = None) -> list[Span]:
        with self._lock:
            return [s for s in self.spans
                    if (name is None or s.name == name)
                    and (service is None or s.service == service)]

    def clear(self) -> None:
        with self._lock:
            self.spans = []


class JsonlExporter:
    """One JSON object per line, appended per span.  Holds ONE
    append-mode handle (exports can run under the scheduler's round
    lock — a per-span open/close syscall trio would tax exactly the
    latency tracing measures); each line is a single write() call, so
    concurrent processes sharing a file interleave by line, never
    mid-record."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._file = None
        self.errors = 0

    def export(self, span: Span) -> None:
        line = json.dumps(span.to_doc(), separators=(",", ":"),
                          default=str) + "\n"
        with self._lock:
            try:
                if self._file is None:
                    # line-buffered: every span line lands on disk at
                    # the write, so a crash loses at most the in-flight
                    # span (the crash-safety the per-span open gave)
                    self._file = open(self.path, "a", buffering=1)
                self._file.write(line)
            except (OSError, ValueError):
                # a full/readonly disk (or a handle someone closed) must
                # not fail the traced operation; retry fresh next span
                self.errors += 1
                self._close_locked()

    # koordlint: guarded-by(self._lock)
    def _close_locked(self) -> None:
        if self._file is not None:
            try:
                self._file.close()
            except OSError:
                pass
            self._file = None

    def close(self) -> None:
        with self._lock:
            self._close_locked()


class Tracer:
    """Thread-local span stack + exporter fan-out + debug ring."""

    def __init__(self, service: str = "", ring_capacity: int = 4096):
        self.service = service
        self._tls = threading.local()
        self._exporters: list = []
        self._lock = threading.Lock()
        #: bounded ring of recently finished spans — the backing store
        #: for /debug/trace/<pod> without any exporter configured
        self.ring: deque[Span] = deque(maxlen=ring_capacity)
        self.export_errors = 0

    # -- configuration -------------------------------------------------------

    def configure(self, service: Optional[str] = None) -> None:
        if service is not None:
            self.service = service

    def add_exporter(self, exporter) -> None:
        with self._lock:
            self._exporters.append(exporter)

    def remove_exporter(self, exporter) -> None:
        with self._lock:
            if exporter in self._exporters:
                self._exporters.remove(exporter)

    # -- context -------------------------------------------------------------

    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def current_span(self) -> Optional[Span]:
        for entry in reversed(self._stack()):
            if isinstance(entry, Span):
                return entry
        return None

    def current_context(self) -> Optional[TraceContext]:
        stack = self._stack()
        if not stack:
            return None
        top = stack[-1]
        return top.context() if isinstance(top, Span) else top

    @contextlib.contextmanager
    def activate(self, ctx: Optional[TraceContext]) -> Iterator[None]:
        """Install a REMOTE parent context for the block.  ``None`` is a
        no-op passthrough (the ambient context, if any, stays active) so
        call sites need no branching."""
        if ctx is None:
            yield
            return
        stack = self._stack()
        stack.append(ctx)
        try:
            yield
        finally:
            stack.pop()

    # -- spans ---------------------------------------------------------------

    def start_span(self, name: str, service: Optional[str] = None,
                   parent: Optional[TraceContext] = None,
                   attributes: Optional[dict] = None) -> Span:
        """Manual-lifecycle span (caller must end()); does NOT enter the
        thread-local stack.  ``parent=None`` uses the current context;
        no current context starts a new trace."""
        pctx = parent if parent is not None else self.current_context()
        trace_id = pctx.trace_id if pctx is not None else _new_trace_id()
        return Span(
            name=name, trace_id=trace_id, span_id=_new_span_id(),
            parent_id=pctx.span_id if pctx is not None else None,
            service=self.service if service is None else service,
            start_time=time.time(), start_perf=time.perf_counter(),
            attributes=attributes, tracer=self,
        )

    @contextlib.contextmanager
    def span(self, name: str, service: Optional[str] = None,
             parent: Optional[TraceContext] = None,
             attributes: Optional[dict] = None) -> Iterator[Span]:
        """Open a span as the current context for the block; ends and
        exports on exit.  An exception marks the span errored and
        re-raises — tracing never swallows failures."""
        sp = self.start_span(name, service=service, parent=parent,
                             attributes=attributes)
        stack = self._stack()
        stack.append(sp)
        try:
            yield sp
        except BaseException as e:
            sp.set_error(repr(e))
            raise
        finally:
            stack.pop()
            sp.end()

    def _export(self, span: Span) -> None:
        self.ring.append(span)
        with self._lock:
            exporters = list(self._exporters)
        for exporter in exporters:
            try:
                exporter.export(span)
            except Exception:  # noqa: BLE001 — an exporter bug must not
                self.export_errors += 1  # fail the traced operation

    # -- debug queries -------------------------------------------------------

    def spans_for_trace(self, trace_id: str) -> list[Span]:
        """Recently finished spans of one trace (ring-bounded), oldest
        first."""
        spans = [s for s in list(self.ring) if s.trace_id == trace_id]
        spans.sort(key=lambda s: s.start_time)
        return spans


#: the process-wide tracer.  Components default their spans' service to
#: ``TRACER.service`` (set by each binary's main via ``configure``) but
#: may override per span — which is what keeps service attribution
#: correct when tests assemble several binaries into one process.
TRACER = Tracer(service=os.environ.get("KOORD_TRACE_SERVICE", ""))

if os.environ.get("KOORD_TRACE_JSONL"):
    TRACER.add_exporter(JsonlExporter(os.environ["KOORD_TRACE_JSONL"]))


# -- module-level conveniences (the common call surface) ---------------------

def configure(service: Optional[str] = None,
              jsonl_path: Optional[str] = None) -> Tracer:
    TRACER.configure(service=service)
    if jsonl_path:
        TRACER.add_exporter(JsonlExporter(jsonl_path))
    return TRACER


def span(name: str, **kwargs):
    return TRACER.span(name, **kwargs)


def activate(ctx: Optional[TraceContext]):
    return TRACER.activate(ctx)


def current_context() -> Optional[TraceContext]:
    return TRACER.current_context()


def current_span() -> Optional[Span]:
    return TRACER.current_span()


def current_trace_id() -> Optional[str]:
    ctx = TRACER.current_context()
    return ctx.trace_id if ctx is not None else None


def inject(doc: dict) -> dict:
    """Copy-on-write inject of the current context into a frame/event
    doc under TRACE_DOC_KEY; returns ``doc`` unchanged when no trace is
    active or the doc already carries one."""
    ctx = TRACER.current_context()
    if ctx is None or TRACE_DOC_KEY in doc:
        return doc
    out = dict(doc)
    out[TRACE_DOC_KEY] = ctx.to_doc()
    return out


def extract(doc: dict) -> Optional[TraceContext]:
    """Pop + decode TRACE_DOC_KEY from a frame/event doc (mutates doc,
    mirroring how the channel pops ``deadline_ms``)."""
    return TraceContext.from_doc(doc.pop(TRACE_DOC_KEY, None))
