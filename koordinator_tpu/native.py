"""ctypes binding to libkoordsys (see ``native/koordsys.cpp``) — the native
fast path for batched cgroup reads and perf-counter CPI, mirroring the
reference's cgo touchpoints (libpfm perf groups, NVML).

Loading order: prebuilt ``native/build/libkoordsys.so`` -> on-demand g++
build into that location -> pure-Python fallback (``available() == False``;
every caller has one). The build happens at most once per process.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Sequence

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC = os.path.join(_REPO_ROOT, "native", "koordsys.cpp")
_LIB_DIR = os.path.join(_REPO_ROOT, "native", "build")
_LIB = os.path.join(_LIB_DIR, "libkoordsys.so")

#: expected ks_version(); a stale prebuilt .so triggers one rebuild
KS_VERSION = 2

_lock = threading.Lock()
#: serializes the g++ compile + dlopen; separate from _lock so fast-path
#: _load() calls never queue behind a running build
_build_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_attempted = False
_build_thread: Optional[threading.Thread] = None


def _build() -> bool:
    if not os.path.exists(_SRC):
        return False
    os.makedirs(_LIB_DIR, exist_ok=True)
    try:
        subprocess.run(
            ["g++", "-O2", "-Wall", "-shared", "-fPIC", "-o", _LIB, _SRC],
            check=True, capture_output=True, timeout=120,
        )
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def _load() -> Optional[ctypes.CDLL]:
    """Non-blocking: returns the lib if already loadable; if the .so is
    missing, kicks the g++ build in a background thread and returns None —
    callers use their Python fallback until the build lands. Never waits on
    a running build (the lock is only held for the quick dlopen, not the
    compile). Use :func:`ensure_built` to wait (tests, daemon init)."""
    global _build_thread
    if _lib is not None or _load_attempted:
        return _lib
    if os.path.exists(_LIB):
        return _load_blocking()
    with _lock:
        if _build_thread is None:
            _build_thread = threading.Thread(
                target=_load_blocking, name="koordsys-build", daemon=True
            )
            _build_thread.start()
    return None


def ensure_built() -> bool:
    """Blocking build+load; True when the native path is usable."""
    return _load_blocking() is not None


def _load_blocking() -> Optional[ctypes.CDLL]:
    global _lib, _load_attempted
    if _load_attempted:
        return _lib
    # The build runs under its own lock: concurrent ensure_built()/background
    # threads serialize here (two g++ runs on one .so corrupt it), while
    # fast-path _load() calls never touch this lock and keep falling back.
    with _build_lock:
        if not _load_attempted and not os.path.exists(_LIB):
            if not _build():
                with _lock:
                    _load_attempted = True
                    return None
    with _lock:
        if _load_attempted:
            return _lib
        _load_attempted = True
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            return None
        lib.ks_version.restype = ctypes.c_int
        if lib.ks_version() != KS_VERSION:
            # stale prebuilt .so from an older source: UNLINK before
            # rebuilding — g++ would otherwise truncate the still-mmapped
            # file under the live handle (UB), and dlopen dedupes by
            # (dev, inode) so only a fresh inode yields a fresh handle
            # (the stale handle itself is leaked, which is harmless)
            try:
                os.unlink(_LIB)
            except OSError:
                return None
            if not _build():
                return None
            try:
                lib = ctypes.CDLL(_LIB)
            except OSError:
                return None
            lib.ks_version.restype = ctypes.c_int
            if lib.ks_version() != KS_VERSION:
                return None
        lib.ks_batch_read.restype = ctypes.c_int
        lib.ks_batch_read.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_int, ctypes.c_char_p,
            ctypes.c_int, ctypes.POINTER(ctypes.c_long),
        ]
        lib.ks_cpi_open.restype = ctypes.c_int
        lib.ks_cpi_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.ks_cpi_read.restype = ctypes.c_int
        lib.ks_cpi_read.argtypes = [
            ctypes.c_int, ctypes.POINTER(ctypes.c_ulonglong),
            ctypes.POINTER(ctypes.c_ulonglong),
        ]
        lib.ks_cpi_close.restype = None
        lib.ks_cpi_close.argtypes = [ctypes.c_int]
        lib.ks_watch_open.restype = ctypes.c_int
        lib.ks_watch_open.argtypes = []
        lib.ks_watch_add.restype = ctypes.c_int
        lib.ks_watch_add.argtypes = [ctypes.c_int, ctypes.c_char_p]
        lib.ks_watch_poll.restype = ctypes.c_int
        lib.ks_watch_poll.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
        ]
        lib.ks_watch_close.restype = None
        lib.ks_watch_close.argtypes = [ctypes.c_int]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


class BatchReader:
    """Reads a fixed set of small files in one native pass per tick.

    Collectors read the same cgroup files every tick, so the path array and
    the result buffer are built once and reused — the per-call cost is one C
    loop of open/read/close. (A naive per-call binding is slower than Python
    IO: the ctypes marshalling dominates.)
    """

    def __init__(self, paths: Sequence[str], max_bytes: int = 4096):
        self.paths = list(paths)
        self.max_bytes = max_bytes
        self._lib = _load()
        self._native_dead = False  # set when the lib stub rejects reads
        if self._lib is not None:
            self._marshal()

    def _marshal(self) -> None:
        n = len(self.paths)
        if n:
            self._c_paths = (ctypes.c_char_p * n)(
                *[p.encode() for p in self.paths]
            )
            self._buf = ctypes.create_string_buffer(n * self.max_bytes)
            self._sizes = (ctypes.c_long * n)()

    def _read_python(self) -> list[Optional[str]]:
        out: list[Optional[str]] = []
        for path in self.paths:
            try:
                with open(path) as f:
                    out.append(f.read(self.max_bytes))
            except OSError:
                out.append(None)
        return out

    def read(self) -> list[Optional[str]]:
        """Current content of every file; None where unreadable."""
        n = len(self.paths)
        if n == 0:
            return []
        if self._lib is None:
            if self._native_dead:
                return self._read_python()
            # the background build may have landed since construction —
            # re-probe so a long-lived reader upgrades to the native path
            self._lib = _load()
            if self._lib is None:
                return self._read_python()
            self._marshal()
        rc = self._lib.ks_batch_read(
            ctypes.cast(self._c_paths, ctypes.POINTER(ctypes.c_char_p)), n,
            self._buf, self.max_bytes, self._sizes,
        )
        if rc < 0:  # non-Linux stub: sizes are not populated
            self._lib = None
            self._native_dead = True
            return self._read_python()
        raw = self._buf.raw
        out = []
        for i in range(n):
            size = self._sizes[i]
            if size < 0:
                out.append(None)
            else:
                start = i * self.max_bytes
                out.append(raw[start: start + size].decode(errors="replace"))
        return out


def batch_read(paths: Sequence[str], max_bytes: int = 4096) -> list[Optional[str]]:
    """One-shot convenience over :class:`BatchReader`."""
    return BatchReader(paths, max_bytes).read()


class DirWatcher:
    """Inotify directory watcher (PLEG fast path; pleg.go's fsnotify role).

    ``open()`` returns False where inotify (or the native lib) is
    unavailable — callers keep their scan path.  ``poll`` returns a list of
    (wd, kind, name): kind "C" = entry appeared, "D" = vanished; a
    (-1, "C", "*") entry signals a kernel queue overflow — treat it as
    "anything may have changed" and rescan.
    """

    def __init__(self):
        self._fd: Optional[int] = None
        self._buf = ctypes.create_string_buffer(16384)

    def open(self) -> bool:
        lib = _load()
        if lib is None:
            return False
        fd = lib.ks_watch_open()
        if fd < 0:
            return False
        self._fd = fd
        return True

    def add(self, path: str) -> Optional[int]:
        """Watch a directory; returns the watch descriptor or None."""
        lib = _load()
        if lib is None or self._fd is None:
            return None
        wd = lib.ks_watch_add(self._fd, path.encode())
        return wd if wd >= 0 else None

    def poll(self, timeout_ms: int = 0) -> list[tuple[int, str, str]]:
        lib = _load()
        if lib is None or self._fd is None:
            return []
        n = lib.ks_watch_poll(self._fd, timeout_ms, self._buf,
                              len(self._buf))
        if n <= 0:
            return []
        out = []
        for line in self._buf.raw[:n].decode(errors="replace").splitlines():
            parts = line.split(" ", 2)
            if len(parts) == 3:
                out.append((int(parts[0]), parts[1], parts[2]))
        return out

    def close(self) -> None:
        lib = _load()
        if lib is not None and self._fd is not None:
            lib.ks_watch_close(self._fd)
        self._fd = None


class CPICounter:
    """Cycles/instructions counters for one cgroup (CPI collector source).

    ``open()`` returns False where perf is unavailable (permissions,
    container, non-Linux) — the CPI collector then disables itself, matching
    the reference's Libpfm4 feature-gate behavior.
    """

    def __init__(self, cgroup_dir: str, n_cpus: int):
        self.cgroup_dir = cgroup_dir
        self.n_cpus = n_cpus
        self._handle: Optional[int] = None

    def open(self) -> bool:
        lib = _load()
        if lib is None:
            return False
        handle = lib.ks_cpi_open(self.cgroup_dir.encode(), self.n_cpus)
        if handle < 0:
            return False
        self._handle = handle
        return True

    def read(self) -> Optional[tuple[int, int]]:
        """(cycles, instructions) cumulative, or None."""
        lib = _load()
        if lib is None or self._handle is None:
            return None
        cycles = ctypes.c_ulonglong()
        instructions = ctypes.c_ulonglong()
        if lib.ks_cpi_read(self._handle, ctypes.byref(cycles),
                           ctypes.byref(instructions)) != 0:
            return None
        return cycles.value, instructions.value

    def close(self) -> None:
        lib = _load()
        if lib is not None and self._handle is not None:
            lib.ks_cpi_close(self._handle)
        self._handle = None
