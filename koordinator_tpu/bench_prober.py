"""Armed bench prober: background device probing with SLO teeth.

ROADMAP item 1's diagnosis work (bench.py's ``_device_alive`` error
kinds, the tools/tpu_probe.sh capture loop) still had two silent modes:

1. a probe that HANGS past its deadline just looped — no alert, no
   artifact, four rounds of undifferentiated zeros (``BENCH_r02-r05``);
2. a successful staged capture sat in ``probe_results/`` until the NEXT
   official bench round promoted it — hours of "we have the number but
   nobody published it".

:class:`ProbeArmer` closes both: every probe attempt lands in the
metrics registry (attempts by outcome, wall-time histogram, a
``bench_probe_hung`` gauge held while the latest probe overran its
deadline), a :class:`~koordinator_tpu.slo_monitor.SloMonitor` evaluates
the ``bench_probe_hang`` burn-rate SLO over those samples — so a wedged
tunnel FIRES an alert with a flight-record dump, exactly like a
scheduling-latency breach — and the FIRST success runs ``publish_fn``
immediately (tools/tpu_probe.sh wires ``bench.py --publish-staged``
there, which stamps the staged capture with provenance and writes it to
``probe_results/published_*.json`` the moment the window opens).

Everything is injectable (probe_fn, clock, monitor, recorder), so the
hang->breach->flight-dump path is proven by a deterministic fake-clock
test (tests/test_bench_prober.py) with no hardware and no sleeps.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

from koordinator_tpu import metrics
from koordinator_tpu.slo_monitor import BurnWindow, SloMonitor, SloSpec

logger = logging.getLogger("koordinator_tpu.bench_prober")

#: outcomes _device_alive can report where the probe HUNG (as opposed
#: to erroring fast): the backend wedged mid-flight
HANG_KINDS = ("probe_kernel_hung", "transfer_stall")


def probe_hang_spec(objective: float = 0.05,
                    fast_window_s: float = 1800.0,
                    fire_burn: float = 4.0) -> SloSpec:
    """The bench-probe SLO: probes may hang at most ``objective`` of the
    time.  Windows are probe-cadence scale (minutes between attempts),
    not request scale, hence the longer fast window and gentler fire
    threshold than the scheduler SLOs."""
    return SloSpec(
        name="bench_probe_hang",
        description="device probes must not hang past their deadline "
                    "(a wedged tunnel is an incident, not a retry loop)",
        kind="gauge",
        metric="koord_scheduler_bench_probe_hung",
        threshold=0.5,
        objective=objective,
        fast=BurnWindow(window_s=fast_window_s, fire_burn=fire_burn),
        slow=BurnWindow(window_s=fast_window_s * 4, fire_burn=1.0),
    )


class ProbeArmer:
    """Retries device probes on a cadence; publishes the first success
    immediately; surfaces hangs as an SLO burn-rate breach.

    ``probe_fn() -> (ok, error_kind, message)`` is bench.py's
    ``_device_alive`` signature.  ``publish_fn()`` runs ONCE, on the
    first successful probe (exceptions are logged, never fatal — the
    window being open matters more than the publisher's health).
    """

    def __init__(
        self,
        probe_fn: Callable[[], tuple[bool, str, str]],
        publish_fn: Optional[Callable[[], None]] = None,
        interval_s: float = 240.0,
        deadline_s: float = 180.0,
        clock=time.monotonic,
        monitor: SloMonitor | None = None,
        flight_recorder=None,
        on_hang: Optional[Callable[[dict], None]] = None,
    ):
        self.probe_fn = probe_fn
        self.publish_fn = publish_fn
        self.interval_s = interval_s
        self.deadline_s = deadline_s
        self.clock = clock
        #: dump target for breach evidence; anything with ``dump_now``
        #: (the scheduler's FlightRecorder) works
        self.flight_recorder = flight_recorder
        self.on_hang = on_hang
        self.monitor = monitor if monitor is not None else SloMonitor(
            specs=[probe_hang_spec()], clock=time.time,
            on_breach=self._breach)
        self.attempts = 0
        self.successes = 0
        self.published = False
        self.publish_outcome: str | None = None
        self.last_outcome: str | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- one probe attempt ---------------------------------------------------

    def tick(self) -> bool:
        """One probe attempt + SLO evaluation; returns probe success."""
        t0 = self.clock()
        try:
            ok, kind, msg = self.probe_fn()
        except Exception as e:  # noqa: BLE001 — a crashing probe is
            # just another outcome, never the armer's death
            ok, kind, msg = False, "probe_error", repr(e)[:300]
        elapsed = self.clock() - t0
        self.attempts += 1
        outcome = "ok" if ok else (kind or "probe_error")
        self.last_outcome = outcome
        metrics.bench_probe_attempts.inc(labels={"outcome": outcome})
        metrics.bench_probe_duration.observe(elapsed)
        hung = (not ok) and (elapsed >= self.deadline_s
                             or kind in HANG_KINDS)
        metrics.bench_probe_hung.set(1.0 if hung else 0.0)
        if ok:
            self.successes += 1
            metrics.bench_probe_window_open.set(1.0)
            if not self.published and self.publish_fn is not None:
                # publish the FIRST capture the moment the window opens
                # — not at the next bench round.  The publisher
                # (bench.py --publish-staged) stamps the staged
                # capture's full 2-D mesh provenance (n_devices +
                # pods x nodes axis split, ISSUE 14) so the published
                # artifact is attributable without the stage file.
                self.published = True
                try:
                    self.publish_fn()
                    self.publish_outcome = "ok"
                except Exception:  # noqa: BLE001 — counted, not fatal
                    self.publish_outcome = "error"
                    logger.exception("probe publish_fn failed")
        elif hung:
            logger.warning("device probe hung (%s after %.0fs): %s",
                           kind, elapsed, msg)
        # the burn-rate evaluation rides every attempt: a run of hung
        # probes burns the budget and fires _breach with flight evidence
        self.monitor.tick()
        return ok

    def _breach(self, spec, doc) -> None:
        logger.warning("bench probe SLO breached: %s", doc.get("name"))
        if self.flight_recorder is not None:
            try:
                self.flight_recorder.dump_now(f"slo:{spec.name}")
            except Exception:  # noqa: BLE001
                logger.exception("flight dump on probe breach failed")
        if self.on_hang is not None:
            try:
                self.on_hang(doc)
            except Exception:  # noqa: BLE001
                logger.exception("on_hang callback failed")

    # -- background cadence --------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop() -> None:
            while not self._stop.is_set():
                try:
                    self.tick()
                except Exception:  # noqa: BLE001 — observer thread
                    logger.exception("probe tick failed")
                if self._stop.wait(self.interval_s):
                    return

        self._thread = threading.Thread(target=loop, name="bench-prober",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        self._thread = None
        if thread is not None:
            thread.join(timeout=5.0)
