"""SLO burn-rate engine: the scheduler evaluating its own latency SLO.

The paper's headline target is a hard latency SLO (50k pods x 10k nodes
under 200ms p99), but until this module nothing in the tree could answer
"are we inside budget right now".  The engine is self-contained — no
external Prometheus:

1. **Sampling.** Every instrument of the in-process metric registries
   (``metrics.ALL_REGISTRIES``) is sampled on an interval into a
   :class:`~koordinator_tpu.koordlet.metriccache.MetricCache` — the same
   numpy-ring/AggregateResult machinery the koordlet's metricsadvisor
   uses for NodeMetric aggregation windows, with query-time retention
   and mean-per-bin downsampling for the slow window.  Counters and
   gauges sample per label set under their exposition name; histograms
   sample ``<name>_bucket`` (per finite ``le``), ``<name>_count`` and
   ``<name>_sum``, so windowed quantiles come from cumulative-count
   deltas exactly like PromQL's ``rate()`` + ``histogram_quantile``.

2. **Burn rates.**  Each :class:`SloSpec` declares an allowed bad
   fraction (the error budget) and evaluates two windows (fast 5m,
   slow 1h by default).  ``burn = bad_fraction / objective``: 1.0 burns
   exactly the budget, 14.4 on the fast window is the classic page-now
   threshold.  Three spec kinds cover the shipped SLOs:

   - ``latency``  — histogram observations above ``threshold`` are bad
     (bucket-interpolated via ``metrics.count_at_or_below``);
   - ``gauge``    — sampled values above ``threshold`` are bad
     (time-in-state budgets: staleness, degraded mode);
   - ``ratio``    — windowed counter delta over a denominator's delta
     (event-rate budgets: solve sheds per round).

3. **Alerts.**  A fast window burning at/above its fire threshold
   flips the SLO breached: ``slo_alerts_total{slo, phase="fire"}``
   increments, ``slo_breached{slo}`` raises, the ``on_breach`` callback
   runs (the scheduler wires the flight recorder's dump there), and the
   breach is served at ``/debug/slo`` on the DebugService and the HTTP
   gateway.  The alert clears with hysteresis: only once the fast burn
   drops below ``clear_ratio * fire`` (so a burn hovering at the
   threshold cannot flap), firing ``phase="clear"``.

Reference anchors: koordinator's node-side self-monitoring treats
metricsadvisor -> metriccache -> NodeMetric aggregation windows as a
first-class subsystem; windowed percentile evaluation as the control
signal follows "A Predictive Autoscaler for Elastic Batch Jobs"
(PAPERS.md); multi-window multi-burn-rate alerting per the SRE workbook.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Callable, Iterable, Optional

from koordinator_tpu import metrics
from koordinator_tpu.koordlet.metriccache import MetricCache

logger = logging.getLogger("koordinator_tpu.slo")

KIND_LATENCY = "latency"
KIND_GAUGE = "gauge"
KIND_RATIO = "ratio"


@dataclasses.dataclass(frozen=True)
class BurnWindow:
    """One evaluation window: how far back, and the burn rate at which
    it counts as breaching."""

    window_s: float
    fire_burn: float


@dataclasses.dataclass(frozen=True)
class SloSpec:
    """One declarative SLO over the sampled registry metrics."""

    name: str
    description: str
    kind: str                   # latency | gauge | ratio
    metric: str                 # full exposition name (with registry prefix)
    objective: float            # allowed bad fraction (the error budget)
    threshold: float = 0.0      # latency bound / gauge bound (kind-specific)
    denominator: str | None = None   # ratio kind: the total-events counter
    fast: BurnWindow = BurnWindow(window_s=300.0, fire_burn=14.4)
    slow: BurnWindow = BurnWindow(window_s=3600.0, fire_burn=1.0)
    #: hysteresis: a firing alert clears only once the fast burn drops
    #: below ``clear_ratio * fast.fire_burn``
    clear_ratio: float = 0.5
    #: mean-per-bin resolution for slow-window gauge aggregation
    #: (0 = raw samples)
    slow_resolution_s: float = 10.0
    #: label filter as sorted (key, value) pairs: only series carrying
    #: ALL of these labels count toward the spec (the per-tenant p99
    #: SLO slices one shared histogram by {tenant=...}); empty = every
    #: label set aggregates, the pre-tenancy behavior
    label_filter: tuple = ()

    def matches_labels(self, labels: dict | None) -> bool:
        if not self.label_filter:
            return True
        if not labels:
            return False
        return all(labels.get(k) == v for k, v in self.label_filter)


def default_specs(latency_threshold_s: float = 0.2,
                  staleness_threshold_s: float = 30.0) -> list[SloSpec]:
    """The shipped scheduler SLOs (the paper's target plus the PR 2
    robustness machinery's health budgets)."""
    return [
        SloSpec(
            name="scheduling_latency_p99",
            description=(f"99% of scheduling-phase observations under "
                         f"{latency_threshold_s * 1000:g}ms (the paper's "
                         "p99 target evaluated per phase observation)"),
            kind=KIND_LATENCY,
            metric="koord_scheduler_scheduling_duration_seconds",
            threshold=latency_threshold_s,
            objective=0.01,
        ),
        SloSpec(
            name="snapshot_staleness",
            description=(f"sync-feed age stays under "
                         f"{staleness_threshold_s:g}s at least 95% of "
                         "the time"),
            kind=KIND_GAUGE,
            metric="koord_scheduler_state_staleness_seconds",
            threshold=staleness_threshold_s,
            objective=0.05,
        ),
        SloSpec(
            name="degraded_time",
            description="degraded-mode time budget: under 1% of time",
            kind=KIND_GAUGE,
            metric="koord_scheduler_degraded_mode",
            threshold=0.5,
            objective=0.01,
        ),
        SloSpec(
            name="pod_e2e_p99",
            description=(f"per-pod journey e2e p99 stays under "
                         f"{latency_threshold_s * 1000:g}ms at least 99% "
                         "of the time (journey-ledger sketch quantiles — "
                         "true arrival-to-ack per-pod latency, not "
                         "round-bucket interpolation; the gauge refreshes "
                         "from the ledger each monitor sweep and the "
                         "budget burns only while it sits over the bar)"),
            kind=KIND_GAUGE,
            metric="koord_scheduler_pod_journey_latency_seconds",
            threshold=latency_threshold_s,
            objective=0.01,
            label_filter=(("q", "0.99"), ("stage", "e2e")),
        ),
        SloSpec(
            name="solve_shed_rate",
            description="under 1% of solve rounds shed on deadline",
            kind=KIND_RATIO,
            metric="koord_scheduler_solve_deadline_shed_total",
            denominator="koord_scheduler_solver_batch_duration_"
                        "seconds_count",
            objective=0.01,
        ),
    ]


def tenant_slo_specs(tenant_names, latency_threshold_s: float = 0.2
                     ) -> list[SloSpec]:
    """Per-tenant p99 latency SLOs (ISSUE 11): one spec per tenant,
    slicing the SHARED ``scheduling_duration_seconds`` histogram by its
    ``{tenant=...}`` label — so one cluster blowing its budget pages as
    that tenant, not as a mushed global p99."""
    return [
        SloSpec(
            name=f"tenant_{name}_latency_p99",
            description=(f"tenant {name}: 99% of scheduling-phase "
                         f"observations under "
                         f"{latency_threshold_s * 1000:g}ms"),
            kind=KIND_LATENCY,
            metric="koord_scheduler_scheduling_duration_seconds",
            threshold=latency_threshold_s,
            objective=0.01,
            label_filter=(("tenant", str(name)),),
        )
        for name in tenant_names
    ]


@dataclasses.dataclass
class _SloState:
    breached: bool = False
    breaches_total: int = 0
    last_fired: float | None = None
    last_cleared: float | None = None
    #: worst burn rate ever observed per window (the soak summary's
    #: "per-SLO worst burn")
    peak_burn: dict = dataclasses.field(
        default_factory=lambda: {"fast": 0.0, "slow": 0.0})


class SloMonitor:
    """Samples the metric registries into ring series and evaluates the
    SLO specs' multi-window burn rates.

    Drive it with :meth:`start` (background thread at
    ``sample_interval_s``) or manually with :meth:`tick` — tests and the
    on-demand ``/debug/slo`` path do the latter, so everything works
    with a fake clock and no thread.
    """

    def __init__(
        self,
        specs: Iterable[SloSpec] | None = None,
        registries: Iterable[metrics.Registry] = metrics.ALL_REGISTRIES,
        sample_interval_s: float = 5.0,
        clock=time.time,
        on_breach: Optional[Callable[[SloSpec, dict], None]] = None,
        cache: MetricCache | None = None,
        capacity_per_series: int = 4096,
        pre_sample: Iterable[Callable[[], None]] = (),
    ):
        self.specs = list(specs) if specs is not None else default_specs()
        self.registries = tuple(registries)
        self.sample_interval_s = sample_interval_s
        self.clock = clock
        #: called on each fire transition as ``on_breach(spec, report)``
        #: — the scheduler wires the flight recorder's dump here.  A
        #: callback exception must never kill the sampler.
        self.on_breach = on_breach
        slow_max = max((s.slow.window_s for s in self.specs), default=3600.0)
        self.cache = cache if cache is not None else MetricCache(
            capacity_per_series=capacity_per_series, clock=clock,
            retention_sec=slow_max * 1.25)
        #: hooks run at the top of every sample sweep, BEFORE the
        #: registries are read — the self-telemetry gauges (RSS, fds,
        #: threads) refresh here so even on-demand /debug/slo and
        #: /debug/steady requests sample current process state.  A hook
        #: exception must never kill the sweep.
        self.pre_sample = list(pre_sample)
        self._state = {spec.name: _SloState() for spec in self.specs}
        self._last_report: dict | None = None
        self._lock = threading.Lock()
        #: serializes the fire/clear state machine: on-demand
        #: /debug/slo requests arrive on gateway threads (ThreadingHTTP
        #: server), and two concurrent evaluations of the same burn
        #: must not both see breached=False and double-fire the alert
        #: (and its on_breach flight dump)
        self._eval_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- sampling ------------------------------------------------------------

    def sample_once(self, now: float | None = None) -> int:
        """One sweep over every registry instrument into the ring
        cache; returns samples appended."""
        now = self.clock() if now is None else now
        for hook in self.pre_sample:
            try:
                hook()
            except Exception:  # noqa: BLE001 — observer, never fatal
                logger.exception("SLO pre-sample hook failed")
        appended = 0
        for reg in self.registries:
            for _, m in reg.items():
                if isinstance(m, metrics.Histogram):
                    for labels, counts, total, total_sum in m.state():
                        for bound, c in zip(m.buckets, counts):
                            self.cache.append(
                                f"{m.name}_bucket", float(c),
                                labels={**labels, "le": f"{bound:g}"},
                                ts=now)
                            appended += 1
                        self.cache.append(f"{m.name}_count", float(total),
                                          labels=labels, ts=now)
                        self.cache.append(f"{m.name}_sum", float(total_sum),
                                          labels=labels, ts=now)
                        appended += 2
                elif isinstance(m, metrics.Counter):   # Gauge subclasses it
                    for labels, value in m.items():
                        self.cache.append(m.name, float(value),
                                          labels=labels, ts=now)
                        appended += 1
        return appended

    # -- windowed math -------------------------------------------------------

    def _window_delta(self, metric: str, labels: dict | None,
                      start: float, end: float) -> float | None:
        """Cumulative-counter delta over [start, end]; None = fewer than
        two samples (no rate is computable).  A negative delta means the
        counter reset mid-window (tests, process restart): the post-reset
        last value is the best available estimate."""
        res = self.cache.query(metric, labels, start=start, end=end)
        if res.count < 2:
            return None
        delta = res.latest() - res.first()
        return delta if delta >= 0 else res.latest()

    def _latency_window(self, spec: SloSpec, start: float, end: float):
        """(bad_fraction, total_delta, p_est) aggregated over every
        label set of the histogram (PromQL ``sum by (le)``)."""
        bucket_metric = f"{spec.metric}_bucket"
        per_le: dict[float, float] = {}
        for labels in self.cache.series_labels(bucket_metric):
            le = labels.get("le")
            if le is None or not spec.matches_labels(labels):
                continue
            delta = self._window_delta(bucket_metric, labels, start, end)
            if delta is None:
                continue
            per_le[float(le)] = per_le.get(float(le), 0.0) + delta
        total = 0.0
        saw_count = False
        for labels in self.cache.series_labels(f"{spec.metric}_count"):
            if not spec.matches_labels(labels):
                continue
            delta = self._window_delta(f"{spec.metric}_count", labels,
                                       start, end)
            if delta is not None:
                total += delta
                saw_count = True
        if not saw_count or not per_le:
            return None, 0.0, 0.0
        bounds = sorted(per_le)
        cum = [per_le[b] for b in bounds]
        if total <= 0:
            return None, 0.0, 0.0
        good = metrics.count_at_or_below(bounds, cum, total, spec.threshold)
        bad_fraction = max(0.0, min(1.0, (total - good) / total))
        p_est = metrics.quantile_from_buckets(bounds, cum, total, 0.99)
        return bad_fraction, total, p_est

    def _gauge_window(self, spec: SloSpec, start: float, end: float,
                      resolution_s: float):
        """Fraction of sampled time above the threshold, over all label
        sets of the gauge."""
        bad = 0.0
        total = 0.0
        label_sets = self.cache.series_labels(spec.metric) or [None]
        for labels in label_sets:
            if not spec.matches_labels(labels):
                continue
            res = self.cache.query(spec.metric, labels, start=start, end=end)
            if resolution_s > 0:
                res = res.downsample(resolution_s)
            if res.empty:
                continue
            bad += float((res.values > spec.threshold).sum())
            total += res.count
        if total == 0:
            return None, 0.0
        return bad / total, total

    def _ratio_window(self, spec: SloSpec, start: float, end: float):
        num = 0.0
        saw_num = False
        for labels in self.cache.series_labels(spec.metric) or [None]:
            if not spec.matches_labels(labels):
                continue
            delta = self._window_delta(spec.metric, labels, start, end)
            if delta is not None:
                num += delta
                saw_num = True
        den = 0.0
        for labels in (self.cache.series_labels(spec.denominator or "")
                       or [None]):
            delta = self._window_delta(spec.denominator, labels, start, end)
            if delta is not None:
                den += delta
        if not saw_num or den <= 0:
            return None, den
        return max(0.0, min(1.0, num / den)), den

    def _evaluate_window(self, spec: SloSpec, window: BurnWindow,
                         which: str, now: float) -> dict:
        start = now - window.window_s
        extra: dict = {}
        if spec.kind == KIND_LATENCY:
            bad, total, p99 = self._latency_window(spec, start, now)
            extra = {"events": total, "p99_s": p99}
        elif spec.kind == KIND_GAUGE:
            resolution = (spec.slow_resolution_s if which == "slow" else 0.0)
            bad, total = self._gauge_window(spec, start, now, resolution)
            extra = {"samples": total}
        elif spec.kind == KIND_RATIO:
            bad, den = self._ratio_window(spec, start, now)
            extra = {"denominator": den}
        else:
            raise ValueError(f"unknown SLO kind {spec.kind!r}")
        burn = (bad / spec.objective) if bad is not None else 0.0
        return {
            "window_s": window.window_s,
            "fire_burn": window.fire_burn,
            "bad_fraction": bad,
            "burn_rate": burn,
            "no_data": bad is None,
            **extra,
        }

    # -- evaluation + alert state machine ------------------------------------

    def evaluate(self, now: float | None = None) -> dict:
        """Evaluate every spec's windows, run the fire/clear state
        machine, and return (and retain) the ``/debug/slo`` body."""
        now = self.clock() if now is None else now
        with self._eval_lock:
            return self._evaluate_locked(now)

    def _evaluate_locked(self, now: float) -> dict:
        slos = []
        for spec in self.specs:
            state = self._state[spec.name]
            windows = {
                "fast": self._evaluate_window(spec, spec.fast, "fast", now),
                "slow": self._evaluate_window(spec, spec.slow, "slow", now),
            }
            for which, win in windows.items():
                metrics.slo_burn_rate.set(
                    win["burn_rate"],
                    labels={"slo": spec.name, "window": which})
                state.peak_burn[which] = max(state.peak_burn[which],
                                             win["burn_rate"])
            fast = windows["fast"]
            fired_now = False
            if (not state.breached and not fast["no_data"]
                    and fast["burn_rate"] >= spec.fast.fire_burn):
                state.breached = True
                state.breaches_total += 1
                state.last_fired = now
                fired_now = True
                metrics.slo_breached.set(1.0, labels={"slo": spec.name})
                metrics.slo_alerts_total.inc(
                    labels={"slo": spec.name, "phase": "fire"})
                logger.warning(
                    "SLO %s breached: fast burn %.1f >= %.1f (%s)",
                    spec.name, fast["burn_rate"], spec.fast.fire_burn,
                    spec.description)
            elif state.breached and (fast["burn_rate"]
                                     < spec.clear_ratio
                                     * spec.fast.fire_burn):
                # hysteresis exit — also reached when the window drained
                # entirely (no_data evaluates as burn 0: no events means
                # no budget is burning)
                state.breached = False
                state.last_cleared = now
                metrics.slo_breached.set(0.0, labels={"slo": spec.name})
                metrics.slo_alerts_total.inc(
                    labels={"slo": spec.name, "phase": "clear"})
                logger.warning("SLO %s recovered: fast burn %.2f",
                               spec.name, fast["burn_rate"])
            doc = {
                "name": spec.name,
                "description": spec.description,
                "kind": spec.kind,
                "metric": spec.metric,
                "objective": spec.objective,
                "threshold": spec.threshold,
                "breached": state.breached,
                "breaches_total": state.breaches_total,
                "last_fired": state.last_fired,
                "last_cleared": state.last_cleared,
                "peak_burn": dict(state.peak_burn),
                "windows": windows,
            }
            slos.append(doc)
            if fired_now and self.on_breach is not None:
                try:
                    self.on_breach(spec, doc)
                except Exception:  # noqa: BLE001 — observer, never fatal
                    logger.exception("SLO on_breach callback failed")
        report = {
            "evaluated_at": now,
            "breached": [d["name"] for d in slos if d["breached"]],
            "slos": slos,
        }
        with self._lock:
            self._last_report = report
        return report

    def tick(self, now: float | None = None) -> dict:
        self.sample_once(now)
        return self.evaluate(now)

    def report(self) -> dict:
        """The latest evaluation; with no background sampler running,
        evaluates on demand (each request adds one sample, so repeated
        scrapes of ``/debug/slo`` build the window organically)."""
        if self._thread is None:
            return self.tick()
        with self._lock:
            report = self._last_report
        return report if report is not None else self.tick()

    # -- background sampler --------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop() -> None:
            while not self._stop.wait(self.sample_interval_s):
                try:
                    self.tick()
                except Exception:  # noqa: BLE001 — observer thread
                    logger.exception("SLO sampler tick failed")

        self._thread = threading.Thread(
            target=loop, name="slo-monitor", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        self._thread = None
        if thread is not None:
            thread.join(timeout=5.0)
