"""ElasticQuotaProfile controller (reference: ``pkg/quota-controller/profile/``):
generate per-tree root ElasticQuotas from node-selector profiles — the
multi-quota-tree feature. A profile selects a set of nodes; the generated
quota's min/max track the selected nodes' total allocatable (scaled by the
profile ratio).
"""

from __future__ import annotations

import hashlib
from typing import Mapping

from koordinator_tpu.api import crds


def _tree_id(profile_name: str) -> str:
    return hashlib.sha256(profile_name.encode()).hexdigest()[:12]


class QuotaProfileController:
    def __init__(self):
        self.profiles: dict[str, crds.ElasticQuotaProfile] = {}
        #: node name -> (labels, allocatable)
        self.nodes: dict[str, tuple[Mapping[str, str], Mapping[str, int]]] = {}

    def upsert_profile(self, profile: crds.ElasticQuotaProfile) -> None:
        self.profiles[profile.name] = profile

    def delete_profile(self, name: str) -> None:
        self.profiles.pop(name, None)

    def upsert_node(self, name: str, labels: Mapping[str, str],
                    allocatable: Mapping[str, int]) -> None:
        self.nodes[name] = (dict(labels), dict(allocatable))

    def delete_node(self, name: str) -> None:
        self.nodes.pop(name, None)

    def reconcile(self) -> list[crds.ElasticQuota]:
        """Regenerate the root ElasticQuota of every profile's tree."""
        out = []
        for profile in self.profiles.values():
            total: dict[str, int] = {}
            for labels, allocatable in self.nodes.values():
                if not all(labels.get(k) == v
                           for k, v in profile.node_selector.items()):
                    continue
                for resource, amount in allocatable.items():
                    total[resource] = total.get(resource, 0) + amount
            ratio = profile.resource_ratio_percent
            scaled = {k: v * ratio // 100 for k, v in total.items()}
            out.append(crds.ElasticQuota(
                name=profile.quota_name or profile.name,
                parent="root",
                min=dict(scaled),
                max=dict(scaled),
                is_parent=True,
                tree_id=_tree_id(profile.name),
                labels=dict(profile.quota_labels),
            ))
        return out
