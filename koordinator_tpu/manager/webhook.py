"""Admission webhooks (reference: ``pkg/webhook/`` — pod mutating
``pod/mutating/cluster_colocation_profile.go`` + ``extended_resource_spec.go``,
pod validating ``pod/validating/``, quota evaluation ``quotaevaluate/``,
ConfigMap validation ``cm/``).

Pods cross this boundary as plain nested dicts (the admission JSON shape);
mutators return the changed pod, validators return error lists.
"""

from __future__ import annotations

import hashlib
from typing import Mapping, Optional

from koordinator_tpu.api import crds, extension as ext
from koordinator_tpu.api.priority import (
    PRIORITY_BATCH_MAX, PRIORITY_BATCH_MIN, PriorityClass, priority_class_of,
)
from koordinator_tpu.api.qos import QoSClass
from koordinator_tpu.manager.sloconfig import validate_config_data  # re-export

__all__ = [
    "PodMutatingWebhook", "PodValidatingWebhook", "QuotaEvaluator",
    "validate_config_data",
]


def _meta(pod: dict) -> dict:
    return pod.setdefault("metadata", {})


def _labels(pod: dict) -> dict:
    return _meta(pod).setdefault("labels", {})


def _annotations(pod: dict) -> dict:
    return _meta(pod).setdefault("annotations", {})


def _selector_matches(selector: Mapping[str, str], labels: Mapping[str, str]) -> bool:
    return all(labels.get(k) == v for k, v in selector.items())


def _stable_fraction(pod: dict) -> float:
    """Deterministic [0,1) hash of the pod identity for canary probability."""
    meta = _meta(pod)
    key = f"{meta.get('namespace', '')}/{meta.get('name', '')}/{meta.get('uid', '')}"
    digest = hashlib.sha256(key.encode()).digest()
    return int.from_bytes(digest[:8], "big") / 2**64


class PodMutatingWebhook:
    """ClusterColocationProfile injection + BE extended-resource translation."""

    def __init__(self, profiles: list[crds.ClusterColocationProfile] | None = None):
        self.profiles = list(profiles or [])

    def set_profiles(self, profiles: list[crds.ClusterColocationProfile]) -> None:
        self.profiles = list(profiles)

    def mutate(self, pod: dict,
               namespace_labels: Mapping[str, str] | None = None) -> dict:
        """Admission mutate: returns the (mutated) pod dict."""
        for profile in self.profiles:
            if not self._profile_matches(profile, pod, namespace_labels or {}):
                continue
            self._apply_profile(profile, pod)
        self._translate_batch_resources(pod)
        return pod

    def _profile_matches(self, profile: crds.ClusterColocationProfile,
                         pod: dict, ns_labels: Mapping[str, str]) -> bool:
        if profile.namespace_selector and not _selector_matches(
            profile.namespace_selector, ns_labels
        ):
            return False
        if profile.pod_selector and not _selector_matches(
            profile.pod_selector, _labels(pod)
        ):
            return False
        if profile.patch_probability < 1.0:
            return _stable_fraction(pod) < profile.patch_probability
        return True

    def _apply_profile(self, profile: crds.ClusterColocationProfile, pod: dict):
        labels = _labels(pod)
        annotations = _annotations(pod)
        if profile.qos_class:
            labels[ext.LABEL_POD_QOS] = profile.qos_class
        if profile.koordinator_priority is not None:
            pod.setdefault("spec", {})["priority"] = profile.koordinator_priority
        if profile.priority_class_name:
            pod.setdefault("spec", {})["priorityClassName"] = (
                profile.priority_class_name
            )
        if profile.scheduler_name:
            pod.setdefault("spec", {})["schedulerName"] = profile.scheduler_name
        labels.update(profile.labels)
        annotations.update(profile.annotations)

    def _translate_batch_resources(self, pod: dict) -> None:
        """extended_resource_spec.go: BE pods' native cpu/memory requests are
        rewritten to batch-cpu (milli) / batch-memory (bytes) so kubelet
        accounts them against the overcommitted pool."""
        qos = QoSClass.parse(_labels(pod).get(ext.LABEL_POD_QOS, ""))
        priority = pod.get("spec", {}).get("priority")
        if qos is not QoSClass.BE:
            return
        if priority is not None and not (
            PRIORITY_BATCH_MIN <= priority <= PRIORITY_BATCH_MAX
        ):
            return
        for container in pod.get("spec", {}).get("containers", []):
            resources = container.setdefault("resources", {})
            for section in ("requests", "limits"):
                values = resources.get(section)
                if not values:
                    continue
                if "cpu" in values:
                    values[ext.RESOURCE_BATCH_CPU] = _cpu_to_milli(values.pop("cpu"))
                if "memory" in values:
                    values[ext.RESOURCE_BATCH_MEMORY] = _mem_to_bytes(
                        values.pop("memory")
                    )


def _cpu_to_milli(value) -> int:
    if isinstance(value, (int, float)):
        return int(value * 1000)
    s = str(value)
    if s.endswith("m"):
        return int(s[:-1])
    return int(float(s) * 1000)


_MEM_SUFFIX = {
    "Ki": 1 << 10, "Mi": 1 << 20, "Gi": 1 << 30, "Ti": 1 << 40,
    "K": 10**3, "M": 10**6, "G": 10**9, "T": 10**12,
}


def _mem_to_bytes(value) -> int:
    if isinstance(value, (int, float)):
        return int(value)
    s = str(value)
    for suffix, mult in _MEM_SUFFIX.items():
        if s.endswith(suffix):
            return int(float(s[: -len(suffix)]) * mult)
    return int(float(s))


#: QoS class -> allowed priority bands (validating webhook compatibility
#: matrix, pod/validating/cluster_colocation_profile.go)
QOS_PRIORITY_COMPAT: dict[QoSClass, tuple[PriorityClass, ...]] = {
    QoSClass.LSE: (PriorityClass.PROD, PriorityClass.NONE),
    QoSClass.LSR: (PriorityClass.PROD, PriorityClass.NONE),
    QoSClass.LS: (PriorityClass.PROD, PriorityClass.MID, PriorityClass.NONE),
    QoSClass.BE: (PriorityClass.MID, PriorityClass.BATCH, PriorityClass.FREE,
                  PriorityClass.NONE),
    QoSClass.SYSTEM: (PriorityClass.NONE,),
    QoSClass.NONE: tuple(PriorityClass),
}


class PodValidatingWebhook:
    def validate(self, pod: dict) -> list[str]:
        errors: list[str] = []
        labels = _labels(pod)
        qos = QoSClass.parse(labels.get(ext.LABEL_POD_QOS, ""))
        priority = pod.get("spec", {}).get("priority")
        band = priority_class_of(priority) if priority is not None else PriorityClass.NONE
        allowed = QOS_PRIORITY_COMPAT.get(qos, tuple(PriorityClass))
        if band not in allowed:
            errors.append(
                f"qosClass {qos.name} incompatible with priority band {band.name}"
            )
        errors.extend(self._verify_batch_resources(pod, qos))
        return errors

    def _verify_batch_resources(self, pod: dict, qos: QoSClass) -> list[str]:
        """verify_*.go: batch resources must come as matched request/limit and
        never mixed with native cpu/memory in the same container."""
        errors = []
        for container in pod.get("spec", {}).get("containers", []):
            resources = container.get("resources", {})
            requests = resources.get("requests", {})
            limits = resources.get("limits", {})
            has_batch = any(
                k in requests or k in limits
                for k in (ext.RESOURCE_BATCH_CPU, ext.RESOURCE_BATCH_MEMORY)
            )
            has_native = "cpu" in requests or "memory" in requests
            if has_batch and has_native:
                errors.append(
                    f"container {container.get('name', '?')}: batch and native "
                    "resources must not be mixed"
                )
            for resource, label in ((ext.RESOURCE_BATCH_CPU, "batch-cpu"),
                                    (ext.RESOURCE_BATCH_MEMORY, "batch-memory")):
                req_b = requests.get(resource)
                lim_b = limits.get(resource)
                if req_b is not None and lim_b is not None and req_b != lim_b:
                    errors.append(
                        f"container {container.get('name', '?')}: {label} "
                        "request must equal limit"
                    )
        return errors


class MultiQuotaTreeAffinity:
    """Multi-quota-tree node affinity injection.

    Reference: ``pkg/webhook/pod/mutating/multi_quota_tree_affinity.go`` — at
    pod CREATE, if the pod's quota (label, else namespace) belongs to a quota
    tree generated from an ElasticQuotaProfile, the profile's node selector is
    ANDed into the pod's scheduling constraints so the pod can only land on
    the tree's nodes.

    We merge into ``spec.nodeSelector`` (our feasibility model's affinity
    input).  A key the pod already pins to a DIFFERENT value stays — the AND
    of conflicting requirements is unsatisfiable either way, and keeping the
    pod's own term surfaces the conflict in diagnosis rather than silently
    rewriting user intent.
    """

    def __init__(self):
        self.quota_tree: dict[str, str] = {}          # quota name -> tree id
        self.tree_selector: dict[str, dict[str, str]] = {}

    def set_quota(self, quota: crds.ElasticQuota) -> None:
        if quota.tree_id:
            self.quota_tree[quota.name] = quota.tree_id

    def set_profile_selector(
        self, tree_id: str, node_selector: Mapping[str, str]
    ) -> None:
        self.tree_selector[tree_id] = dict(node_selector)

    def mutate(self, pod: dict, operation: str = "CREATE") -> bool:
        """Returns True when the pod was mutated."""
        if operation != "CREATE":
            return False
        labels = _labels(pod)
        quota = labels.get(ext.LABEL_QUOTA_NAME) or pod.get(
            "metadata", {}
        ).get("namespace", "")
        tree = self.quota_tree.get(quota)
        if tree is None:
            return False
        selector = self.tree_selector.get(tree)
        if not selector:
            return False
        spec = pod.setdefault("spec", {})
        node_selector = spec.setdefault("nodeSelector", {})
        changed = False
        for k, v in selector.items():
            if k not in node_selector:
                node_selector[k] = v
                changed = True
        return changed


class QuotaEvaluator:
    """Admission-time quota charge (webhook/quotaevaluate): check the pod's
    request against its ElasticQuota's remaining runtime up the tree."""

    def __init__(self, quotas: dict[str, crds.ElasticQuota] | None = None):
        self.quotas = dict(quotas or {})
        self.used: dict[str, dict[str, int]] = {}

    def set_quota(self, quota: crds.ElasticQuota) -> None:
        self.quotas[quota.name] = quota

    def _chain(self, name: str) -> list[crds.ElasticQuota]:
        chain = []
        while name and name != "root":
            quota = self.quotas.get(name)
            if quota is None:
                break
            chain.append(quota)
            name = quota.parent
        return chain

    def admit(self, quota_name: str, request: Mapping[str, int]) -> Optional[str]:
        """None = admitted (and charged); otherwise the rejection reason."""
        chain = self._chain(quota_name)
        if not chain:
            return None  # no quota -> no constraint (reference default-allow)
        for quota in chain:
            used = self.used.get(quota.name, {})
            for resource, amount in request.items():
                cap = quota.max.get(resource)
                if cap is None:
                    continue
                if used.get(resource, 0) + amount > cap:
                    return (
                        f"exceeded quota {quota.name}: {resource} "
                        f"{used.get(resource, 0)}+{amount} > {cap}"
                    )
        for quota in chain:
            used = self.used.setdefault(quota.name, {})
            for resource, amount in request.items():
                used[resource] = used.get(resource, 0) + amount
        return None

    def release(self, quota_name: str, request: Mapping[str, int]) -> None:
        for quota in self._chain(quota_name):
            used = self.used.get(quota.name, {})
            for resource, amount in request.items():
                used[resource] = max(0, used.get(resource, 0) - amount)
