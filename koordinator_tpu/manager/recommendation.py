"""Recommendation controller (reference: ``apis/analysis/v1alpha1/
recommendation_types.go:96`` + the koord-manager recommender): VPA-style
per-workload resource recommendations from decaying usage histograms.

One HistogramBank row per workload; samples arrive as (workload, cpu, mem)
observations (fed from NodeMetric pod metrics); the recommendation is
p90 * (1 + margin) — all workloads answered in one tensor query.
"""

from __future__ import annotations

import time
from typing import Optional

import jax.numpy as jnp
import numpy as np

from koordinator_tpu.api import crds
from koordinator_tpu.prediction import histogram as hist

MIB = 1 << 20


class RecommendationController:
    def __init__(self, capacity: int = 1024, half_life_sec: float = 24 * 3600.0,
                 percentile: float = 0.9, margin_pct: int = 15, clock=time.time):
        self.cpu_buckets = hist.default_cpu_buckets()
        self.mem_buckets = hist.default_memory_buckets()
        self.cpu_bank = hist.HistogramBank.zeros(capacity, self.cpu_buckets,
                                                 half_life_sec)
        self.mem_bank = hist.HistogramBank.zeros(capacity, self.mem_buckets,
                                                 half_life_sec)
        self.percentile = percentile
        self.margin_pct = margin_pct
        self.clock = clock
        self._rows: dict[str, int] = {}
        self._free = list(range(capacity - 1, -1, -1))

    def _row(self, workload: str) -> Optional[int]:
        row = self._rows.get(workload)
        if row is None and self._free:
            row = self._free.pop()
            self._rows[workload] = row
        return row

    def observe(self, samples: list[tuple[str, float, float]],
                ts: Optional[float] = None) -> None:
        """samples: (workload_ref, cpu_milli, mem_mib) per pod observation."""
        rows, cpus, mems = [], [], []
        for workload, cpu, mem in samples:
            row = self._row(workload)
            if row is None:
                continue
            rows.append(row)
            cpus.append(cpu)
            mems.append(mem)
        if not rows:
            return
        t = jnp.float32(self.clock() if ts is None else ts)
        r = jnp.asarray(np.asarray(rows, np.int32))
        self.cpu_bank = hist.add_samples(
            self.cpu_bank, self.cpu_buckets, r,
            jnp.asarray(np.asarray(cpus, np.float32)), t,
        )
        self.mem_bank = hist.add_samples(
            self.mem_bank, self.mem_buckets, r,
            jnp.asarray(np.asarray(mems, np.float32)), t,
        )

    def recommend_all(self) -> list[crds.Recommendation]:
        """One tensor pass over every workload's histograms."""
        if not self._rows:
            return []
        cpu_p = np.asarray(
            hist.percentile(self.cpu_bank, self.cpu_buckets, self.percentile)
        )
        mem_p = np.asarray(
            hist.percentile(self.mem_bank, self.mem_buckets, self.percentile)
        )
        scale = 1.0 + self.margin_pct / 100.0
        now = self.clock()
        out = []
        for workload, row in sorted(self._rows.items()):
            if cpu_p[row] <= 0 and mem_p[row] <= 0:
                continue
            out.append(crds.Recommendation(
                name=workload.replace("/", "-"),
                workload_ref=workload,
                target_cpu_milli=int(cpu_p[row] * scale),
                target_memory_bytes=int(mem_p[row] * scale) * MIB,
                update_time=now,
            ))
        return out
