"""NodeMetric controller (reference: ``pkg/slo-controller/nodemetric/
nodemetric_controller.go:58`` Reconcile): ensure every node has a NodeMetric
CR carrying the collect policy, and track report staleness.
"""

from __future__ import annotations

import time
from typing import Optional

from koordinator_tpu.api import crds
from koordinator_tpu.manager.sloconfig import ColocationConfig


class NodeMetricController:
    def __init__(self, config: Optional[ColocationConfig] = None, clock=time.time):
        self.config = config or ColocationConfig()
        self.clock = clock
        self._metrics: dict[str, crds.NodeMetric] = {}

    def _spec(self) -> crds.NodeMetricSpec:
        return crds.NodeMetricSpec(
            aggregate_duration_seconds=self.config.metric_aggregate_duration_seconds,
            report_interval_seconds=self.config.metric_report_interval_seconds,
        )

    def upsert_node(self, name: str) -> crds.NodeMetric:
        """Node exists -> ensure its NodeMetric exists with current spec."""
        current = self._metrics.get(name)
        spec = self._spec()
        if current is None:
            current = crds.NodeMetric(name=name, spec=spec)
        elif current.spec != spec:
            current = crds.NodeMetric(name=name, spec=spec, status=current.status)
        self._metrics[name] = current
        return current

    def delete_node(self, name: str) -> None:
        self._metrics.pop(name, None)

    def report_status(self, name: str, status: crds.NodeMetricStatus) -> None:
        """The agent's periodic status update."""
        metric = self._metrics.get(name) or crds.NodeMetric(name=name, spec=self._spec())
        self._metrics[name] = crds.NodeMetric(
            name=name, spec=metric.spec, status=status
        )

    def get(self, name: str) -> Optional[crds.NodeMetric]:
        return self._metrics.get(name)

    def is_expired(self, name: str) -> bool:
        """Stale beyond the update threshold (feeds degrade decisions)."""
        metric = self._metrics.get(name)
        if metric is None or metric.status.update_time == 0 \
                or getattr(metric.status, "degraded", False):
            return True
        return (
            self.clock() - metric.status.update_time
            > self.config.update_time_threshold_seconds
        )
