"""Colocation resource math: Batch/Mid allocatable over the whole cluster.

Semantics from the reference slo-controller
(``pkg/slo-controller/noderesource/plugins/util/util.go``):

- CalculateBatchResourceByPolicy (:56):
    byUsage:           cap - margin - max(systemUsed, reserved) - hpUsed
    byRequest:         cap - margin - reserved - hpRequest
    byMaxUsageRequest: cap - margin - max(systemUsed, reserved) - hpMaxUsedReq
  each clamped at 0, then optionally capped at cap * batchThresholdPercent.
  CPU supports usage/maxUsageRequest; memory supports all three policies.
- GetNodeSafetyMargin (:368): margin = cap * (100 - reclaimThresholdPercent)/100.
- CalculateMidResourceByPolicy (:190):
    mid = min( min(prodReclaimable, nodeUnused) + unallocated * midUnallocatedPercent,
               cap * midThresholdPercent )
  with negative reclaimable clamped to 0.

All integer math; percent products stay within int32 because quantities are
bounded by MAX_QUANTITY = 2^31/100 (state/cluster_state.py). Go multiplies in
float64 and truncates — for operands this small the float64 product is exact,
so integer ``(a*pct)//100`` is bit-identical.

Every function takes (..., N) leading batch shapes, so per-NUMA-zone
calculation (the reference's zone-aware batch resource) is the same call with
a (N, Z, R)-shaped input.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS, ResourceDim

# CalculatePolicy codes (configuration.CalculatePolicy)
POLICY_USAGE = 0
POLICY_REQUEST = 1
POLICY_MAX_USAGE_REQUEST = 2


@struct.dataclass
class ColocationStrategy:
    """The slo-controller-config colocation strategy, tensor form.

    Mirrors configuration.ColocationStrategy fields used by the resource
    plugins; percentages are int32 scalars, 0-100 (a threshold of 100 = no
    effective cap, matching nil semantics where noted).
    """

    cpu_reclaim_threshold_pct: jax.Array      # default 60
    memory_reclaim_threshold_pct: jax.Array   # default 65
    cpu_calculate_policy: jax.Array           # POLICY_USAGE | POLICY_MAX_USAGE_REQUEST
    memory_calculate_policy: jax.Array        # any of the three
    batch_cpu_threshold_pct: jax.Array        # 100 = nil (no cap)
    batch_memory_threshold_pct: jax.Array     # 100 = nil (no cap)
    mid_cpu_threshold_pct: jax.Array          # default 10
    mid_memory_threshold_pct: jax.Array       # default 10
    mid_unallocated_pct: jax.Array            # default 0

    @classmethod
    def default(cls) -> "ColocationStrategy":
        i32 = lambda v: jnp.int32(v)
        return cls(
            cpu_reclaim_threshold_pct=i32(60),
            memory_reclaim_threshold_pct=i32(65),
            cpu_calculate_policy=i32(POLICY_USAGE),
            memory_calculate_policy=i32(POLICY_USAGE),
            batch_cpu_threshold_pct=i32(100),
            batch_memory_threshold_pct=i32(100),
            mid_cpu_threshold_pct=i32(10),
            mid_memory_threshold_pct=i32(10),
            mid_unallocated_pct=i32(0),
        )


def _pct(value: jnp.ndarray, pct: jnp.ndarray) -> jnp.ndarray:
    """value * pct / 100 with exact integer truncation (see module docstring)."""
    return value * pct // 100


def node_safety_margin(
    capacity_cpu: jnp.ndarray,
    capacity_mem: jnp.ndarray,
    strategy: ColocationStrategy,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(N,) safety margins: cap * (100 - reclaimThresholdPercent) / 100."""
    return (
        _pct(capacity_cpu, 100 - strategy.cpu_reclaim_threshold_pct),
        _pct(capacity_mem, 100 - strategy.memory_reclaim_threshold_pct),
    )


def _batch_one_dim(
    capacity, margin, reserved, system_used, hp_used, hp_req, hp_max_used_req,
    policy, threshold_pct, allow_request_policy,
):
    """The three-policy batch formula for one resource dimension, (N,)."""
    sys_or_reserved = jnp.maximum(system_used, reserved)
    by_usage = jnp.maximum(capacity - margin - sys_or_reserved - hp_used, 0)
    by_request = jnp.maximum(capacity - margin - reserved - hp_req, 0)
    by_max = jnp.maximum(capacity - margin - sys_or_reserved - hp_max_used_req, 0)

    alloc = by_usage
    alloc = jnp.where(policy == POLICY_MAX_USAGE_REQUEST, by_max, alloc)
    if allow_request_policy:
        alloc = jnp.where(policy == POLICY_REQUEST, by_request, alloc)
    return jnp.minimum(alloc, _pct(capacity, threshold_pct))


def batch_allocatable(
    capacity_cpu: jnp.ndarray,     # (..., N) node cpu capacity (mcores)
    capacity_mem: jnp.ndarray,     # (..., N) node memory capacity (MiB)
    system_used_cpu: jnp.ndarray,
    system_used_mem: jnp.ndarray,
    reserved_cpu: jnp.ndarray,     # max(node annotation, kubelet reserved)
    reserved_mem: jnp.ndarray,
    hp_used_cpu: jnp.ndarray,      # sum of Prod/Mid pods' usage
    hp_used_mem: jnp.ndarray,
    hp_req_cpu: jnp.ndarray,       # sum of Prod/Mid pods' requests
    hp_req_mem: jnp.ndarray,
    hp_max_used_req_cpu: jnp.ndarray,  # sum of per-pod max(request, usage)
    hp_max_used_req_mem: jnp.ndarray,
    strategy: ColocationStrategy,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(batch_cpu, batch_mem) allocatable, each (..., N).

    Parity: CalculateBatchResourceByPolicy — cpu ignores the byRequest policy
    (only usage/maxUsageRequest supported), memory supports all three.
    """
    margin_cpu, margin_mem = node_safety_margin(
        capacity_cpu, capacity_mem, strategy
    )
    batch_cpu = _batch_one_dim(
        capacity_cpu, margin_cpu, reserved_cpu, system_used_cpu,
        hp_used_cpu, hp_req_cpu, hp_max_used_req_cpu,
        strategy.cpu_calculate_policy, strategy.batch_cpu_threshold_pct,
        allow_request_policy=False,
    )
    batch_mem = _batch_one_dim(
        capacity_mem, margin_mem, reserved_mem, system_used_mem,
        hp_used_mem, hp_req_mem, hp_max_used_req_mem,
        strategy.memory_calculate_policy, strategy.batch_memory_threshold_pct,
        allow_request_policy=True,
    )
    return batch_cpu, batch_mem


def mid_allocatable(
    capacity_cpu: jnp.ndarray,
    capacity_mem: jnp.ndarray,
    prod_reclaimable_cpu: jnp.ndarray,  # from the usage forecaster
    prod_reclaimable_mem: jnp.ndarray,
    node_unused_cpu: jnp.ndarray,
    node_unused_mem: jnp.ndarray,
    unallocated_cpu: jnp.ndarray,
    unallocated_mem: jnp.ndarray,
    strategy: ColocationStrategy,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(mid_cpu, mid_mem) allocatable, each (..., N).

    Parity: CalculateMidResourceByPolicy —
      min( clamp0(min(prodReclaimable, nodeUnused)) + unallocated * midUnallocatedPct,
           cap * midThresholdPct ).
    """
    def one(reclaimable, unused, unallocated, cap, threshold_pct):
        base = jnp.maximum(jnp.minimum(reclaimable, unused), 0)
        base = base + _pct(unallocated, strategy.mid_unallocated_pct)
        return jnp.minimum(base, _pct(cap, threshold_pct))

    return (
        one(prod_reclaimable_cpu, node_unused_cpu, unallocated_cpu,
            capacity_cpu, strategy.mid_cpu_threshold_pct),
        one(prod_reclaimable_mem, node_unused_mem, unallocated_mem,
            capacity_mem, strategy.mid_memory_threshold_pct),
    )


def _pct_wide(value: jnp.ndarray, pct: jnp.ndarray) -> jnp.ndarray:
    """value * pct / 100 for pct that may exceed 100: split into whole
    multiples plus a <100 remainder so each int32 product stays in range
    (value <= MAX_QUANTITY guarantees value*99 < 2^31). The result is clamped
    at MAX_QUANTITY so amplified capacities keep the int32 invariant every
    downstream percent/score kernel relies on."""
    from koordinator_tpu.state.cluster_state import MAX_QUANTITY

    out = value * (pct // 100) + value * (pct % 100) // 100
    return jnp.minimum(out, MAX_QUANTITY)


def cpu_normalization(capacity_cpu: jnp.ndarray, ratio_pct: jnp.ndarray) -> jnp.ndarray:
    """CPU normalization: scale node CPU capacity by a per-model benchmark
    ratio (pkg/slo-controller/noderesource/plugins/cpunormalization).
    ratio_pct is (N,) int32 percent (100 = 1.0; may exceed 100)."""
    return _pct_wide(capacity_cpu, ratio_pct)


def amplify_capacity(capacity: jnp.ndarray, amplification_pct: jnp.ndarray) -> jnp.ndarray:
    """Node resource amplification (apis/extension/node_resource_amplification):
    raw capacity scaled by an amplification ratio >= 100%."""
    return _pct_wide(capacity, amplification_pct)


def update_batch_mid_in_state(state, batch_cpu, batch_mem, mid_cpu, mid_mem):
    """Write computed Batch/Mid allocatable into the cluster-state tensors
    (the NodeSync step that patches node.status.allocatable upstream)."""
    alloc = state.node_allocatable
    alloc = alloc.at[:, ResourceDim.BATCH_CPU].set(batch_cpu)
    alloc = alloc.at[:, ResourceDim.BATCH_MEMORY].set(batch_mem)
    alloc = alloc.at[:, ResourceDim.MID_CPU].set(mid_cpu)
    alloc = alloc.at[:, ResourceDim.MID_MEMORY].set(mid_mem)
    return state.replace(node_allocatable=alloc)
