"""Central-controller math (the koord-manager equivalents).

The reference's slo-controller reconcilers compute per-node results one node
per Reconcile call; here the same formulas are tensor ops over every node at
once, feeding the device-resident cluster state directly (and still exportable
per node for protocol compatibility).

- ``noderesource`` -- the colocation formulas: Batch/Mid allocatable,
  safety margins, CPU normalization and node resource amplification.
"""

from koordinator_tpu.manager.noderesource import (
    ColocationStrategy,
    batch_allocatable,
    mid_allocatable,
    node_safety_margin,
)

__all__ = [
    "ColocationStrategy",
    "batch_allocatable",
    "mid_allocatable",
    "node_safety_margin",
]
