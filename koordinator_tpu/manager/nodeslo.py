"""NodeSLO controller (reference: ``pkg/slo-controller/nodeslo/
nodeslo_controller.go:127`` Reconcile): render the cluster ConfigMap
strategies into one NodeSLO per node, honoring node-selector overrides.
"""

from __future__ import annotations

from typing import Mapping

from koordinator_tpu.api import crds
from koordinator_tpu.manager import sloconfig


def render_node_slo(
    node_name: str,
    node_labels: Mapping[str, str],
    config_data: Mapping[str, str],
) -> crds.NodeSLO:
    """One node's NodeSLO from the slo-controller-config data."""
    threshold = sloconfig.parse_threshold_strategy(config_data, node_labels)
    burst = sloconfig.parse_cpu_burst_strategy(config_data, node_labels)
    return crds.NodeSLO(
        name=node_name,
        resource_used_threshold_with_be=threshold,
        cpu_burst_strategy=burst,
    )


class NodeSLOController:
    """Keeps the rendered NodeSLO set in sync with nodes + config changes."""

    def __init__(self, config_data: Mapping[str, str] | None = None):
        self._config_data = dict(config_data or {})
        self._nodes: dict[str, Mapping[str, str]] = {}  # name -> labels
        self._rendered: dict[str, crds.NodeSLO] = {}

    def update_config(self, config_data: Mapping[str, str]) -> list[str]:
        """New ConfigMap content; re-renders everything. Returns the names of
        NodeSLOs whose content changed."""
        errors = sloconfig.validate_config_data(config_data)
        if errors:
            # invalid config is rejected wholesale (webhook admission path);
            # keep serving the last good config — reference behavior.
            return []
        self._config_data = dict(config_data)
        return self._reconcile_all()

    def upsert_node(self, name: str, labels: Mapping[str, str]) -> bool:
        """Node added/labels changed; returns True if its NodeSLO changed."""
        self._nodes[name] = dict(labels)
        new = render_node_slo(name, labels, self._config_data)
        changed = self._rendered.get(name) != new
        self._rendered[name] = new
        return changed

    def delete_node(self, name: str) -> None:
        self._nodes.pop(name, None)
        self._rendered.pop(name, None)

    def _reconcile_all(self) -> list[str]:
        changed = []
        for name, labels in self._nodes.items():
            if self.upsert_node(name, labels):
                changed.append(name)
        return changed

    def get(self, name: str) -> crds.NodeSLO | None:
        return self._rendered.get(name)
