"""ElasticQuota-CR admission webhook: topology validation + default filling.

The reference validates quota *objects* at admission so a malformed tree
never reaches the runtime calculators
(`pkg/webhook/elasticquota/quota_topology.go:62` ``ValidAddQuota``, ``:103``
``ValidUpdateQuota``, ``:159`` ``ValidDeleteQuota``;
``quota_topology_check.go:39`` self-item checks, ``:92`` topology checks;
``plugin_check_quota_meta_validate.go`` wires them into the webhook).  This
module is the same admission gate for the repo: the scheduler's
``quota/tree.py`` may assume every CR it sees has passed here.

The validator keeps its own lightweight topology mirror (name -> quota,
parent -> children) — the webhook is an admission-time authority, fed by
the same informer stream as the manager, not a view of the runtime tree.
"""

from __future__ import annotations

from typing import Callable, Iterable, Mapping, Optional

from koordinator_tpu.api import crds

#: reserved quota groups (extension.RootQuotaName / SystemQuotaName /
#: DefaultQuotaName): never deletable, root/system never modifiable
ROOT_QUOTA = "root"
SYSTEM_QUOTA = "koordinator-system-quota"
DEFAULT_QUOTA = "koordinator-default-quota"


def _neg_dims(rl: Mapping[str, int]) -> list[str]:
    return sorted(k for k, v in rl.items() if v < 0)


def _le_completely(a: Mapping[str, int], b: Mapping[str, int]) -> bool:
    """util.LessThanOrEqualCompletely: every dim of a <= b (missing b dim
    counts as 0)."""
    return all(v <= b.get(k, 0) for k, v in a.items())


def _add(a: Mapping[str, int], b: Mapping[str, int]) -> dict[str, int]:
    out = dict(a)
    for k, v in b.items():
        out[k] = out.get(k, 0) + v
    return out


class QuotaTopologyValidator:
    """Admission-time ElasticQuota validation + mutation (default filling).

    ``validate_add`` / ``validate_update`` / ``validate_delete`` return a
    list of error strings — empty means admit.  ``fill_defaults`` is the
    mutating side (`quota_topology.go:216 fillQuotaDefaultInformation`):
    parent defaults to root, tree id inherits from the parent, shared
    weight defaults to max.
    """

    def __init__(
        self,
        enable_update_resource_key: bool = False,
        guarantee_usage: bool = False,
        has_pods_fn: Optional[Callable[[str], bool]] = None,
    ) -> None:
        self.quotas: dict[str, crds.ElasticQuota] = {}
        self.children: dict[str, set[str]] = {ROOT_QUOTA: set()}
        #: AnnotationQuotaNamespaces binding: namespace -> quota name
        self.namespace_to_quota: dict[str, str] = {}
        #: per-quota Status.Used (fed by the quota controller) for the
        #: max >= used strict check
        self.used: dict[str, dict[str, int]] = {}
        self.enable_update_resource_key = enable_update_resource_key
        self.guarantee_usage = guarantee_usage
        #: answers "does any pod reference this quota" (the reference lists
        #: pods by the quota label); None = assume no pods
        self.has_pods_fn = has_pods_fn

    # -- feed ---------------------------------------------------------------

    def set_used(self, name: str, used: Mapping[str, int]) -> None:
        self.used[name] = dict(used)

    def _has_pods(self, name: str) -> bool:
        return bool(self.has_pods_fn and self.has_pods_fn(name))

    # -- mutating side ------------------------------------------------------

    def fill_defaults(
        self, quota: crds.ElasticQuota,
        namespaces: Iterable[str] = (),
    ) -> crds.ElasticQuota:
        """Default-fill parent / tree id / shared weight.  Raises ValueError
        when the declared parent is missing (fill needs its tree id)."""
        if quota.name == ROOT_QUOTA:
            return quota
        parent = quota.parent or ROOT_QUOTA
        tree_id = quota.tree_id
        if not tree_id and parent != ROOT_QUOTA:
            pinfo = self.quotas.get(parent)
            if pinfo is None:
                raise ValueError(
                    f"fill quota {quota.name} failed, parent not exist")
            tree_id = pinfo.tree_id
        shared = quota.shared_weight or dict(quota.max)
        return crds.ElasticQuota(
            name=quota.name, namespace=quota.namespace, parent=parent,
            min=quota.min, max=quota.max, shared_weight=shared,
            is_parent=quota.is_parent,
            allow_lent_resource=quota.allow_lent_resource,
            guarantee_usage=quota.guarantee_usage, tree_id=tree_id,
            labels=quota.labels,
        )

    # -- validating side ----------------------------------------------------

    def validate_add(
        self, quota: crds.ElasticQuota,
        namespaces: Iterable[str] = (),
    ) -> list[str]:
        errors: list[str] = []
        if quota.name in self.quotas:
            return [f"quota already exists: {quota.name}"]
        for ns in namespaces:
            owner = self.namespace_to_quota.get(ns)
            if owner is not None:
                errors.append(
                    f"namespace {ns} is already bound to quota {owner}")
        errors += self._self_item(quota)
        errors += self._topology(None, quota)
        if errors:
            return errors
        self._apply(quota, namespaces)
        return []

    def validate_update(
        self, new: crds.ElasticQuota,
        namespaces: Iterable[str] = (),
    ) -> list[str]:
        old = self.quotas.get(new.name)
        if old == new:
            return []
        # IsForbiddenModify (extension/elastic_quota.go:105): system/root
        # quota groups are immutable
        if new.name in (SYSTEM_QUOTA, ROOT_QUOTA):
            return [f"invalid quota {new.name}"]
        if old is None:
            return [f"quota not found: {new.name}"]
        errors: list[str] = []
        for ns in namespaces:
            owner = self.namespace_to_quota.get(ns)
            if owner is not None and owner != new.name:
                errors.append(
                    f"namespace {ns} is already bound to quota {owner}")
        errors += self._self_item(new)
        errors += self._topology(old, new)
        if errors:
            return errors
        self._unapply(old)
        self._apply(new, namespaces)
        return []

    def validate_delete(self, name: str) -> list[str]:
        if name in (SYSTEM_QUOTA, ROOT_QUOTA, DEFAULT_QUOTA):
            return [f"can not delete quota group: {name}"]
        quota = self.quotas.get(name)
        if quota is None:
            return [f"quota not found: {name}"]
        kids = self.children.get(name, set())
        if kids:
            return [f"delete quota failed, quota {name} has "
                    f"{len(kids)} child quotas"]
        if self._has_pods(name):
            return [f"delete quota failed, quota {name} has bound pods"]
        self._unapply(quota)
        self.children.pop(name, None)
        self.used.pop(name, None)
        return []

    # -- checks (quota_topology_check.go) -----------------------------------

    def _self_item(self, q: crds.ElasticQuota) -> list[str]:
        """validateQuotaSelfItem (:39): non-negative min/max/sharedWeight,
        min keys included in max, min <= max, max >= used."""
        errors = []
        for field, rl in (("max", q.max), ("min", q.min),
                          ("sharedWeight", q.shared_weight)):
            neg = _neg_dims(rl)
            if neg:
                errors.append(
                    f"{q.name} quota {field} < 0 in dimensions {neg}")
        for key, val in q.min.items():
            if key not in q.max:
                errors.append(
                    f"resourceKey {key} of quota {q.name} is in min "
                    f"but not in max")
            elif q.max[key] < val:
                errors.append(
                    f"resourceKey {key} of quota {q.name} min {val} > "
                    f"max {q.max[key]}")
        # strict max >= used on every used dim (the reference scopes this
        # to AnnotationMaxStrictCheckResourceKeys; used is fed by set_used)
        for key, used_val in self.used.get(q.name, {}).items():
            if key in q.max and q.max[key] < used_val:
                errors.append(
                    f"resourceKey {key} of quota {q.name} max "
                    f"{q.max[key]} < used {used_val}")
        return errors

    def _topology(
        self, old: Optional[crds.ElasticQuota], new: crds.ElasticQuota,
    ) -> list[str]:
        """validateQuotaTopology (:92): parent-change rules, tree ids,
        parent existence, key consistency, min sums, guarantee."""
        if new.name == ROOT_QUOTA:
            return []
        errors = []
        errors += self._is_parent_change(old, new)
        errors += self._tree_id(old, new)
        if errors:
            return errors
        # leaf directly under root skips the structural checks (:107)
        if new.parent == ROOT_QUOTA and not new.is_parent:
            return []
        errors += self._parent_info(new)
        if errors:
            return errors
        errors += self._key_consistency(new)
        errors += self._min_sums(old, new)
        if self.guarantee_usage:
            errors += self._guarantee(new)
        return errors

    def _is_parent_change(self, old, new) -> list[str]:
        """checkIsParentChange (:162): with children, isParent cannot go
        false; with bound pods, isParent cannot go true."""
        if old is None or old.is_parent == new.is_parent:
            return []
        if self.children.get(old.name) and not new.is_parent:
            return [f"quota {old.name} has children, isParent cannot "
                    f"become false"]
        if new.is_parent and self._has_pods(old.name):
            return [f"quota {old.name} has bound pods, isParent cannot "
                    f"become true"]
        return []

    def _tree_id(self, old, new) -> list[str]:
        """checkTreeID (:131): immutable, and consistent with parent and
        children."""
        errors = []
        if old is not None and old.tree_id != new.tree_id:
            errors.append(f"{new.name} tree id changed "
                          f"[{old.tree_id}] vs [{new.tree_id}]")
        if new.parent != ROOT_QUOTA:
            pinfo = self.quotas.get(new.parent)
            if pinfo is not None and new.tree_id != pinfo.tree_id:
                errors.append(
                    f"{new.name} tree id differs from parent "
                    f"{new.parent}: [{new.tree_id}] vs [{pinfo.tree_id}]")
        for child in self.children.get(new.name, ()):  # update case
            cinfo = self.quotas.get(child)
            if cinfo is not None and cinfo.tree_id != new.tree_id:
                errors.append(
                    f"{new.name} tree id differs from child {child}: "
                    f"[{new.tree_id}] vs [{cinfo.tree_id}]")
        return errors

    def _parent_info(self, new) -> list[str]:
        """checkParentQuotaInfo (:186): parent exists and isParent."""
        if new.parent == ROOT_QUOTA:
            return []
        pinfo = self.quotas.get(new.parent)
        if pinfo is None:
            return [f"{new.name} has parent {new.parent} which does "
                    f"not exist"]
        if not pinfo.is_parent:
            return [f"{new.name} has parent {new.parent} whose isParent "
                    f"is false"]
        return []

    def _key_consistency(self, new) -> list[str]:
        """checkSubAndParentGroupQuotaKey (:205): max keys same as the
        parent's (or included, with ElasticQuotaEnableUpdateResourceKey);
        min keys always included in the parent's."""
        errors = []

        def included(parent_rl, child_rl):
            return all(k in parent_rl for k in child_rl)

        def check_pair(parent_name, parent_rl_max, parent_rl_min,
                       child_name, child_rl_max, child_rl_min):
            if self.enable_update_resource_key:
                if not included(parent_rl_max, child_rl_max):
                    errors.append(
                        f"{child_name}'s max keys are not all included "
                        f"in {parent_name}'s")
            else:
                if set(parent_rl_max) != set(child_rl_max):
                    errors.append(
                        f"{child_name}'s max keys are not the same as "
                        f"{parent_name}'s")
            if not included(parent_rl_min, child_rl_min):
                errors.append(
                    f"{child_name}'s min keys are not all included in "
                    f"{parent_name}'s")

        if new.parent != ROOT_QUOTA:
            pinfo = self.quotas[new.parent]
            check_pair(new.parent, pinfo.max, pinfo.min,
                       new.name, new.max, new.min)
        for child in self.children.get(new.name, ()):
            cinfo = self.quotas.get(child)
            if cinfo is not None:
                check_pair(new.name, new.max, new.min,
                           child, cinfo.max, cinfo.min)
        return errors

    def _min_sums(self, old, new) -> list[str]:
        """checkMinQuotaValidate (:265): siblings' min sum <= parent min;
        children's min sum <= the quota's own min."""
        errors = []
        if new.parent != ROOT_QUOTA:
            sibling_sum: dict[str, int] = {}
            for sib in self.children.get(new.parent, ()):
                if sib == new.name:
                    continue
                sinfo = self.quotas.get(sib)
                if sinfo is not None:
                    sibling_sum = _add(sibling_sum, sinfo.min)
            total = _add(sibling_sum, new.min)
            if not _le_completely(total, self.quotas[new.parent].min):
                errors.append(
                    f"all siblings' min > parent min, parent: "
                    f"{new.parent}")
        child_sum: dict[str, int] = {}
        for child in self.children.get(new.name, ()):
            cinfo = self.quotas.get(child)
            if cinfo is not None:
                child_sum = _add(child_sum, cinfo.min)
        if child_sum and not _le_completely(child_sum, new.min):
            errors.append(
                f"all children's min > quota min, quota: {new.name}")
        return errors

    def _guarantee(self, new) -> list[str]:
        """checkGuaranteedForMin (ElasticQuotaGuaranteeUsage): shrinking
        min below the quota's current used breaks the guarantee."""
        used = self.used.get(new.name)
        if not used:
            return []
        bad = sorted(k for k, v in new.min.items() if used.get(k, 0) > v)
        if bad and new.guarantee_usage:
            return [f"min < guaranteed used in dimensions {bad} "
                    f"for {new.name}"]
        return []

    # -- topology bookkeeping ----------------------------------------------

    def _apply(self, quota: crds.ElasticQuota,
               namespaces: Iterable[str]) -> None:
        self.quotas[quota.name] = quota
        self.children.setdefault(quota.name, set())
        self.children.setdefault(quota.parent, set()).add(quota.name)
        for ns in namespaces:
            self.namespace_to_quota[ns] = quota.name

    def _unapply(self, quota: crds.ElasticQuota) -> None:
        self.quotas.pop(quota.name, None)
        self.children.get(quota.parent, set()).discard(quota.name)
        stale = [ns for ns, q in self.namespace_to_quota.items()
                 if q == quota.name]
        for ns in stale:
            del self.namespace_to_quota[ns]
