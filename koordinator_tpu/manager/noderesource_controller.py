"""NodeResource controller: the colocation loop's central math (reference:
``pkg/slo-controller/noderesource/noderesource_controller.go:71`` Reconcile +
the plugin framework ``framework/extender_plugin.go`` with
ResourceCalculate / NodePrepare / NodeSync stages).

TPU-native redesign: the reference reconciles one node per event; here one
tick batches EVERY node's formula into a single jitted tensor call over
(N,)-vectors (manager/noderesource.py kernels), then per-node host logic
(degrade, diff-threshold sync suppression, device sync) consumes the result.

Units: cpu milli-cores, memory MiB (resources.py convention; NodeMetric
reports bytes and is converted on ingestion).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Mapping, Optional

import jax
import jax.numpy as jnp
import numpy as np

from koordinator_tpu.api import crds, extension as ext
from koordinator_tpu.manager import noderesource as formula
from koordinator_tpu.manager.sloconfig import ColocationConfig

MIB = 1 << 20


@dataclasses.dataclass
class NodeRecord:
    """Everything the controller knows about one node."""

    name: str
    cpu_capacity_milli: int
    mem_capacity_mib: int
    labels: Mapping[str, str] = dataclasses.field(default_factory=dict)
    annotations: Mapping[str, str] = dataclasses.field(default_factory=dict)
    metric: Optional[crds.NodeMetricStatus] = None
    device: Optional[crds.Device] = None
    #: sums over Prod+Mid pods on the node (from the pod informer)
    hp_request_cpu_milli: int = 0
    hp_request_mem_mib: int = 0
    #: per-pod max(request, usage) summed (for the maxUsageRequest policy)
    hp_max_used_req_cpu_milli: int = 0
    hp_max_used_req_mem_mib: int = 0
    #: prod reclaimable from the usage forecaster (mid-resource input)
    prod_reclaimable_cpu_milli: int = 0
    prod_reclaimable_mem_mib: int = 0
    #: pre-aggregated HP (Prod+Mid) usage — set when the record comes
    #: from the wire (the koordlet's node_usage hp_usage array) instead
    #: of a full NodeMetric with per-pod rows; overrides the
    #: pods_metrics sum when not None
    hp_used_cpu_milli: Optional[int] = None
    hp_used_mem_mib: Optional[int] = None
    #: last synced values (for diff-threshold / no-op patch suppression)
    last_batch_cpu: int = -1
    last_batch_mem: int = -1
    last_mid_cpu: int = -1
    last_mid_mem: int = -1
    last_device_resources: Optional[Mapping[str, int]] = None
    last_degraded: bool = False


@dataclasses.dataclass(frozen=True)
class NodePatch:
    """The NodeSync output: extended resources to patch onto node status."""

    name: str
    batch_cpu_milli: int
    batch_mem_mib: int
    mid_cpu_milli: int
    mid_mem_mib: int
    device_resources: Mapping[str, int] = dataclasses.field(default_factory=dict)
    degraded: bool = False


def _policy_code(policy: str) -> int:
    return {
        "usage": formula.POLICY_USAGE,
        "request": formula.POLICY_REQUEST,
        "maxUsageRequest": formula.POLICY_MAX_USAGE_REQUEST,
    }.get(policy, formula.POLICY_USAGE)


class NodeResourceController:
    def __init__(self, config: Optional[ColocationConfig] = None,
                 clock=time.time):
        self.config = config or ColocationConfig(enable=True)
        self.clock = clock
        self._batched = jax.jit(self._compute_batched)

    # ---- the batched tensor stage ------------------------------------------

    @staticmethod
    def _compute_batched(inputs: dict, strategy: formula.ColocationStrategy):
        batch_cpu, batch_mem = formula.batch_allocatable(
            inputs["cap_cpu"], inputs["cap_mem"],
            inputs["sys_used_cpu"], inputs["sys_used_mem"],
            inputs["reserved_cpu"], inputs["reserved_mem"],
            inputs["hp_used_cpu"], inputs["hp_used_mem"],
            inputs["hp_req_cpu"], inputs["hp_req_mem"],
            inputs["hp_max_cpu"], inputs["hp_max_mem"],
            strategy,
        )
        unallocated_cpu = jnp.maximum(
            inputs["cap_cpu"] - inputs["hp_req_cpu"], 0
        )
        unallocated_mem = jnp.maximum(
            inputs["cap_mem"] - inputs["hp_req_mem"], 0
        )
        node_unused_cpu = jnp.maximum(inputs["cap_cpu"] - inputs["node_used_cpu"], 0)
        node_unused_mem = jnp.maximum(inputs["cap_mem"] - inputs["node_used_mem"], 0)
        mid_cpu, mid_mem = formula.mid_allocatable(
            inputs["cap_cpu"], inputs["cap_mem"],
            inputs["reclaim_cpu"], inputs["reclaim_mem"],
            node_unused_cpu, node_unused_mem,
            unallocated_cpu, unallocated_mem,
            strategy,
        )
        return batch_cpu, batch_mem, mid_cpu, mid_mem

    def _strategy(self) -> formula.ColocationStrategy:
        c = self.config
        i32 = jnp.int32
        return formula.ColocationStrategy(
            cpu_reclaim_threshold_pct=i32(c.cpu_reclaim_threshold_percent),
            memory_reclaim_threshold_pct=i32(c.memory_reclaim_threshold_percent),
            cpu_calculate_policy=i32(_policy_code(c.cpu_calculate_policy)),
            memory_calculate_policy=i32(_policy_code(c.memory_calculate_policy)),
            batch_cpu_threshold_pct=i32(100),
            batch_memory_threshold_pct=i32(100),
            mid_cpu_threshold_pct=i32(c.mid_cpu_threshold_percent),
            mid_memory_threshold_pct=i32(c.mid_memory_threshold_percent),
            mid_unallocated_pct=i32(c.mid_unallocated_percent),
        )

    # ---- reconcile ----------------------------------------------------------

    def reconcile(self, nodes: list[NodeRecord]) -> list[NodePatch]:
        """One controller tick over every node. Returns patches for nodes
        whose batch/mid resources changed beyond the diff threshold (plus all
        degraded nodes)."""
        if not nodes:
            return []
        now = self.clock()
        n = len(nodes)

        def col(fn) -> np.ndarray:
            return np.asarray([fn(r) for r in nodes], np.int32)

        def metric_or(r: NodeRecord, fn, default=0) -> int:
            return fn(r.metric) if r.metric is not None else default

        # CPU normalization + amplification prepare stage (annotations).
        cap_cpu_raw = col(lambda r: r.cpu_capacity_milli)
        norm_pct = col(
            lambda r: ext.get_cpu_normalization_ratio_pct(r.annotations)
        )
        amp = [ext.get_node_amplification_ratios(r.annotations) for r in nodes]
        amp_cpu_pct = np.asarray(
            [a.get("cpu", 100) for a in amp], np.int32
        )
        cap_cpu = np.asarray(
            formula.cpu_normalization(jnp.asarray(cap_cpu_raw), jnp.asarray(norm_pct))
        )
        cap_cpu = np.asarray(
            formula.amplify_capacity(jnp.asarray(cap_cpu), jnp.asarray(amp_cpu_pct))
        )

        inputs = {
            "cap_cpu": jnp.asarray(cap_cpu),
            "cap_mem": jnp.asarray(col(lambda r: r.mem_capacity_mib)),
            "sys_used_cpu": jnp.asarray(col(
                lambda r: metric_or(r, lambda m: m.system_usage.cpu_milli))),
            "sys_used_mem": jnp.asarray(col(
                lambda r: metric_or(r, lambda m: m.system_usage.memory_bytes // MIB))),
            "reserved_cpu": jnp.asarray(col(
                lambda r: int(ext.get_node_reservation(r.annotations).get("cpu", 0)))),
            "reserved_mem": jnp.asarray(col(
                lambda r: int(ext.get_node_reservation(r.annotations).get("memory", 0)))),
            "hp_used_cpu": jnp.asarray(col(lambda r: self._hp_used_cpu(r))),
            "hp_used_mem": jnp.asarray(col(lambda r: self._hp_used_mem(r))),
            "hp_req_cpu": jnp.asarray(col(lambda r: r.hp_request_cpu_milli)),
            "hp_req_mem": jnp.asarray(col(lambda r: r.hp_request_mem_mib)),
            "hp_max_cpu": jnp.asarray(col(lambda r: r.hp_max_used_req_cpu_milli)),
            "hp_max_mem": jnp.asarray(col(lambda r: r.hp_max_used_req_mem_mib)),
            "node_used_cpu": jnp.asarray(col(
                lambda r: metric_or(r, lambda m: m.node_usage.cpu_milli))),
            "node_used_mem": jnp.asarray(col(
                lambda r: metric_or(r, lambda m: m.node_usage.memory_bytes // MIB))),
            "reclaim_cpu": jnp.asarray(col(lambda r: r.prod_reclaimable_cpu_milli)),
            "reclaim_mem": jnp.asarray(col(lambda r: r.prod_reclaimable_mem_mib)),
        }
        batch_cpu, batch_mem, mid_cpu, mid_mem = map(
            np.asarray, self._batched(inputs, self._strategy())
        )

        from koordinator_tpu import metrics

        patches: list[NodePatch] = []
        for i, record in enumerate(nodes):
            degraded = self._degraded(record, now)
            b_cpu = 0 if degraded else int(batch_cpu[i])
            b_mem = 0 if degraded else int(batch_mem[i])
            m_cpu = 0 if degraded else int(mid_cpu[i])
            m_mem = 0 if degraded else int(mid_mem[i])
            devres = self._device_resources(record)
            # observability: every tick refreshes the gauges, even for nodes
            # below the diff threshold that emit no patch
            metrics.batch_resource_allocatable.set(
                float(b_cpu), labels={"node": record.name,
                                      "resource": "batch-cpu"})
            metrics.batch_resource_allocatable.set(
                float(b_mem), labels={"node": record.name,
                                      "resource": "batch-memory"})
            metrics.node_metric_expired.set(
                1.0 if degraded else 0.0, labels={"node": record.name})
            if degraded and record.last_degraded:
                # already zeroed — but device info comes from the Device CR,
                # independent of metric freshness, so device changes still sync
                if record.last_device_resources == devres:
                    continue
            elif not degraded and not self._needs_sync(
                record, b_cpu, b_mem, m_cpu, m_mem, devres
            ):
                continue
            record.last_batch_cpu, record.last_batch_mem = b_cpu, b_mem
            record.last_mid_cpu, record.last_mid_mem = m_cpu, m_mem
            record.last_device_resources = dict(devres)
            record.last_degraded = degraded
            patches.append(NodePatch(
                name=record.name,
                batch_cpu_milli=b_cpu, batch_mem_mib=b_mem,
                mid_cpu_milli=m_cpu, mid_mem_mib=m_mem,
                device_resources=devres,
                degraded=degraded,
            ))
        return patches

    # ---- helper stages ------------------------------------------------------

    def _hp_used_cpu(self, record: NodeRecord) -> int:
        from koordinator_tpu.api.priority import is_hp_band

        if record.hp_used_cpu_milli is not None:
            return record.hp_used_cpu_milli
        if record.metric is None:
            return 0
        return sum(
            p.usage.cpu_milli for p in record.metric.pods_metrics
            if is_hp_band(p.qos_class, p.priority)
        )

    def _hp_used_mem(self, record: NodeRecord) -> int:
        from koordinator_tpu.api.priority import is_hp_band

        if record.hp_used_mem_mib is not None:
            return record.hp_used_mem_mib
        if record.metric is None:
            return 0
        return sum(
            p.usage.memory_bytes // MIB for p in record.metric.pods_metrics
            if is_hp_band(p.qos_class, p.priority)
        )

    def _degraded(self, record: NodeRecord, now: float) -> bool:
        """NodeMetric stale beyond degradeTimeMinutes -> zero out colocation
        resources (the reference's degrade mode)."""
        if record.metric is None:
            return True
        if getattr(record.metric, "degraded", False):
            return True  # koordlet reported collectors-silent explicitly
        age = now - record.metric.update_time
        return age > self.config.degrade_time_minutes * 60

    def _needs_sync(self, record: NodeRecord, b_cpu: int, b_mem: int,
                    m_cpu: int, m_mem: int,
                    devres: Mapping[str, int]) -> bool:
        """diff-threshold suppression (isResourceDiff): skip the patch when
        the relative change of every dimension is below the threshold and
        mid/device resources are unchanged. A node recovering from degrade
        always syncs."""
        if record.last_batch_cpu < 0 or record.last_degraded:
            return True
        if record.last_device_resources != devres:
            return True
        threshold = self.config.resource_diff_threshold

        def differs(old: int, new: int) -> bool:
            if old == new:
                return False
            base = max(old, 1)
            return abs(new - old) / base > threshold

        return (
            differs(record.last_batch_cpu, b_cpu)
            or differs(record.last_batch_mem, b_mem)
            or differs(record.last_mid_cpu, m_cpu)
            or differs(record.last_mid_mem, m_mem)
        )

    def _device_resources(self, record: NodeRecord) -> dict[str, int]:
        """gpudeviceresource/rdmadevicereource NodeSync: Device CR ->
        node-level extended resources."""
        if record.device is None:
            return {}
        out: dict[str, int] = {}
        for dev in record.device.devices:
            if not dev.health:
                continue
            if dev.type == "gpu":
                out[ext.RESOURCE_GPU] = out.get(ext.RESOURCE_GPU, 0) + 100
                out[ext.RESOURCE_GPU_CORE] = out.get(ext.RESOURCE_GPU_CORE, 0) + 100
                mem = dev.resources.get(ext.RESOURCE_GPU_MEMORY, 0)
                out[ext.RESOURCE_GPU_MEMORY] = (
                    out.get(ext.RESOURCE_GPU_MEMORY, 0) + mem
                )
            elif dev.type == "rdma":
                out[ext.RESOURCE_RDMA] = out.get(ext.RESOURCE_RDMA, 0) + 100
            else:
                # xpu / tpu / vendor devices: publish their declared resource
                # quantities as-is (xpudeviceresource parity)
                for res, amount in dev.resources.items():
                    out[res] = out.get(res, 0) + int(amount)
        return out
