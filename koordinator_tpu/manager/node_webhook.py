"""Node admission webhooks: resource amplification + slo-config conflict.

The reference runs mutating and validating webhooks on Node objects
(`pkg/webhook/node/mutating/mutating_handler.go`,
`node/plugins/resourceamplification/resource_amplification.go`,
`node/plugins/sloconfig/slo_plugin.go`).  The amplification plugin is the
admission-time ENFORCEMENT point for the amplification math that the
manager computes (manager/noderesource.py ``amplify_capacity``): kubelet's
raw allocatable is preserved in an annotation and the amplified values are
written into the node's allocatable at admission, so every consumer of the
Node object sees amplified capacity without racing the controller.

Node documents here are plain dicts —
``{"name", "labels": {}, "annotations": {}, "allocatable": {"cpu": m,
"memory": bytes}}`` — the same dialect the pod webhooks use.
"""

from __future__ import annotations

import json
from typing import Mapping, Optional

from koordinator_tpu.api import extension as ext

#: only cpu and memory amplify (resource_amplification.go:55)
SUPPORTED_RESOURCES = ("cpu", "memory")


def _annotations(node: dict) -> dict:
    return node.setdefault("annotations", {})


def _get_ratios(annotations: Mapping[str, str]) -> dict[str, float]:
    """Amplification ratios as direct multipliers (>= 1; e.g. 1.5 = +50%
    capacity, matching the reference's float ratio annotation); raises
    ValueError on a malformed annotation (the validating side rejects
    these)."""
    raw = annotations.get(ext.ANNOTATION_NODE_AMPLIFICATION, "")
    if not raw:
        return {}
    data = json.loads(raw)  # ValueError on bad JSON
    if not isinstance(data, dict):
        raise ValueError("amplification ratio must be a JSON object")
    out = {}
    for key, val in data.items():
        if not isinstance(val, (int, float)) or isinstance(val, bool):
            raise ValueError(f"amplification ratio {key} must be a number")
        if val < 1:
            raise ValueError(
                f"amplification ratio {key}={val} must be >= 1")
        out[key] = val
    return out


class NodeResourceAmplificationPlugin:
    """Mutating: maintain raw allocatable + write amplified capacity
    (resource_amplification.go:93 handleUpdate)."""

    name = "NodeResourceAmplificationPlugin"

    def admit(self, node: dict, old_node: Optional[dict],
              operation: str = "UPDATE") -> None:
        if operation == "CREATE":
            return
        ann = _annotations(node)
        if not ann.get(ext.ANNOTATION_NODE_AMPLIFICATION):
            # feature turned off: restore kubelet's raw allocatable BEFORE
            # dropping the saved copy — in this dialect nothing else
            # rewrites allocatable, so popping alone would leave amplified
            # capacity on the node forever (and discard the only baseline)
            raw_saved = ann.pop(ext.ANNOTATION_NODE_RAW_ALLOCATABLE, None)
            if raw_saved and node.get("allocatable"):
                try:
                    original = json.loads(raw_saved)
                except json.JSONDecodeError:
                    return
                for resource in SUPPORTED_RESOURCES:
                    if resource in original:
                        node["allocatable"][resource] = original[resource]
            return
        alloc = node.get("allocatable")
        if not alloc:
            return
        ratios = _get_ratios(ann)  # propagates ValueError to the handler

        # save/refresh kubelet's raw values when absent or when kubelet
        # changed them (only kubelet overwrites native allocatable fields)
        raw_saved = ann.get(ext.ANNOTATION_NODE_RAW_ALLOCATABLE)
        if raw_saved is None or self._kubelet_changed(node, old_node):
            original = {r: alloc[r] for r in SUPPORTED_RESOURCES
                        if r in alloc}
            if original:
                ann[ext.ANNOTATION_NODE_RAW_ALLOCATABLE] = json.dumps(
                    original, sort_keys=True)
        else:
            try:
                original = json.loads(raw_saved)
            except json.JSONDecodeError as e:
                raise ValueError(f"bad raw-allocatable annotation: {e}")

        # allocatable = raw * ratio, per supported dim with ratio > 1;
        # missing raw dims stay untouched (resource_amplification.go:145)
        for resource in SUPPORTED_RESOURCES:
            ratio = ratios.get(resource)
            if ratio is None or ratio <= 1:
                continue
            value = original.get(resource)
            if value is None:
                continue
            alloc[resource] = int(value * ratio)

    @staticmethod
    def _kubelet_changed(node: dict, old_node: Optional[dict]) -> bool:
        if old_node is None:
            return False
        old_alloc = old_node.get("allocatable") or {}
        new_alloc = node.get("allocatable") or {}
        return any(old_alloc.get(r) != new_alloc.get(r)
                   for r in SUPPORTED_RESOURCES)


class NodeMutatingWebhook:
    """Mutating handler: run the amplification plugin, return errors
    (non-empty = deny, matching the reference's errored admission)."""

    def __init__(self) -> None:
        self.plugins = [NodeResourceAmplificationPlugin()]

    def mutate(self, node: dict, old_node: Optional[dict] = None,
               operation: str = "UPDATE") -> list[str]:
        errors = []
        for plugin in self.plugins:
            try:
                plugin.admit(node, old_node, operation)
            except ValueError as e:
                errors.append(f"{plugin.name}: {e}")
        return errors


class SLOConfigConflictPlugin:
    """Validating: a node's labels must not select conflicting node-level
    strategy overrides in the slo-controller ConfigMap
    (slo_plugin.go:70 checkConflict).  Conflict = the node matches more
    than one nodeStrategy of the same config key — merge order would then
    be ambiguous for this node."""

    name = "SLOControllerConfigConflict"

    def __init__(self, config_data_fn=None):
        #: returns the live slo-controller ConfigMap data ({} when absent);
        #: absence skips the check (the reference logs and admits)
        self.config_data_fn = config_data_fn or (lambda: {})

    def validate(self, node: dict, old_node: Optional[dict],
                 operation: str = "UPDATE") -> list[str]:
        if operation == "UPDATE" and old_node is not None \
                and node.get("labels") == old_node.get("labels"):
            return []
        config = self.config_data_fn() or {}
        labels = node.get("labels") or {}
        errors = []
        for key, raw in config.items():
            try:
                parsed = json.loads(raw)
            except (json.JSONDecodeError, TypeError):
                continue  # CM validation rejects these elsewhere
            if not isinstance(parsed, dict):
                continue
            strategies = parsed.get("nodeStrategies")
            if not isinstance(strategies, list):
                continue
            matched = []
            for i, strat in enumerate(strategies):
                sel = (strat.get("nodeSelector") or {}).get(
                    "matchLabels", {})
                if sel and all(labels.get(k) == v
                               for k, v in sel.items()):
                    matched.append(strat.get("name", f"strategy[{i}]"))
            if len(matched) > 1:
                errors.append(
                    f"{key}: node {node.get('name', '?')} matches "
                    f"conflicting node strategies {matched}")
        return errors


class NodeValidatingWebhook:
    """Validating handler: amplification annotation sanity + slo-config
    conflicts.  Returns error strings (empty = admit)."""

    def __init__(self, config_data_fn=None):
        self.slo_plugin = SLOConfigConflictPlugin(config_data_fn)

    def validate(self, node: dict, old_node: Optional[dict] = None,
                 operation: str = "UPDATE") -> list[str]:
        errors = []
        try:
            _get_ratios(node.get("annotations") or {})
        except ValueError as e:
            errors.append(f"amplification: {e}")
        errors += self.slo_plugin.validate(node, old_node, operation)
        return errors
