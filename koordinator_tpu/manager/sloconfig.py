"""The cluster-level SLO config model (reference: ``pkg/util/sloconfig/`` —
the ``slo-controller-config`` ConfigMap schema: per-cluster strategies with
per-node-selector overrides, defaults, validation).

The config arrives as JSON dicts (the ConfigMap data values); ``parse_*``
merge cluster defaults with the first matching node-selector override —
exactly the reference's GetNodeXxxStrategy merge order.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Mapping, Optional

from koordinator_tpu.api import crds

# ConfigMap keys (sloconfig/config.go)
KEY_COLOCATION = "colocation-config"
KEY_RESOURCE_THRESHOLD = "resource-threshold-config"
KEY_RESOURCE_QOS = "resource-qos-config"
KEY_CPU_BURST = "cpu-burst-config"
KEY_SYSTEM = "system-config"


@dataclasses.dataclass(frozen=True)
class ColocationConfig:
    """colocation-config entry (sloconfig/colocation_config.go)."""

    enable: bool = False
    metric_aggregate_duration_seconds: int = 300
    metric_report_interval_seconds: int = 60
    cpu_reclaim_threshold_percent: int = 60
    memory_reclaim_threshold_percent: int = 65
    memory_calculate_policy: str = "usage"      # usage | request | maxUsageRequest
    cpu_calculate_policy: str = "usage"
    degrade_time_minutes: int = 15
    update_time_threshold_seconds: int = 300
    resource_diff_threshold: float = 0.1
    mid_cpu_threshold_percent: int = 10
    mid_memory_threshold_percent: int = 10
    mid_unallocated_percent: int = 0


def _matches(selector: Mapping[str, str], labels: Mapping[str, str]) -> bool:
    return all(labels.get(k) == v for k, v in selector.items())


def _merged(cluster: dict, overrides: list[dict],
            node_labels: Mapping[str, str]) -> dict:
    """Cluster strategy + first matching nodeStrategies entry (field-level
    merge, override wins)."""
    out = dict(cluster)
    for entry in overrides:
        selector = entry.get("nodeSelector", {}).get("matchLabels", {})
        if _matches(selector, node_labels):
            out.update({k: v for k, v in entry.items() if k != "nodeSelector"})
            break
    return out


def _load(config_data: Mapping[str, str], key: str) -> tuple[dict, list[dict]]:
    raw = config_data.get(key, "")
    if not raw:
        return {}, []
    try:
        parsed = json.loads(raw)
    except json.JSONDecodeError:
        return {}, []
    if not isinstance(parsed, dict):
        return {}, []
    overrides = parsed.pop("nodeStrategies", [])
    return parsed, overrides if isinstance(overrides, list) else []


def parse_colocation_config(
    config_data: Mapping[str, str],
    node_labels: Mapping[str, str] | None = None,
) -> ColocationConfig:
    cluster, overrides = _load(config_data, KEY_COLOCATION)
    merged = _merged(cluster, overrides, node_labels or {})
    fields = {f.name: f for f in dataclasses.fields(ColocationConfig)}
    camel = {
        "enable": "enable",
        "metricAggregateDurationSeconds": "metric_aggregate_duration_seconds",
        "metricReportIntervalSeconds": "metric_report_interval_seconds",
        "cpuReclaimThresholdPercent": "cpu_reclaim_threshold_percent",
        "memoryReclaimThresholdPercent": "memory_reclaim_threshold_percent",
        "memoryCalculatePolicy": "memory_calculate_policy",
        "cpuCalculatePolicy": "cpu_calculate_policy",
        "degradeTimeMinutes": "degrade_time_minutes",
        "updateTimeThresholdSeconds": "update_time_threshold_seconds",
        "resourceDiffThreshold": "resource_diff_threshold",
        "midCPUThresholdPercent": "mid_cpu_threshold_percent",
        "midMemoryThresholdPercent": "mid_memory_threshold_percent",
        "midUnallocatedPercent": "mid_unallocated_percent",
    }
    kwargs = {}
    for camel_key, snake in camel.items():
        if camel_key in merged and snake in fields:
            kwargs[snake] = merged[camel_key]
    return ColocationConfig(**kwargs)


def parse_threshold_strategy(
    config_data: Mapping[str, str],
    node_labels: Mapping[str, str] | None = None,
) -> crds.ResourceThresholdStrategy:
    cluster, overrides = _load(config_data, KEY_RESOURCE_THRESHOLD)
    merged = _merged(cluster, overrides, node_labels or {})
    return crds.ResourceThresholdStrategy(
        enable=merged.get("enable", False),
        cpu_suppress_threshold_percent=merged.get("cpuSuppressThresholdPercent", 65),
        cpu_suppress_policy=merged.get("cpuSuppressPolicy", "cpuset"),
        cpu_evict_be_usage_threshold_percent=merged.get(
            "cpuEvictBEUsageThresholdPercent", 90
        ),
        cpu_evict_be_satisfaction_lower_percent=merged.get(
            "cpuEvictBESatisfactionLowerPercent", 0
        ),
        cpu_evict_be_satisfaction_upper_percent=merged.get(
            "cpuEvictBESatisfactionUpperPercent", 0
        ),
        cpu_evict_time_window_seconds=merged.get("cpuEvictTimeWindowSeconds", 60),
        memory_evict_threshold_percent=merged.get("memoryEvictThresholdPercent", 70),
        memory_evict_lower_percent=merged.get("memoryEvictLowerPercent", 0),
    )


def parse_cpu_burst_strategy(
    config_data: Mapping[str, str],
    node_labels: Mapping[str, str] | None = None,
) -> crds.CPUBurstStrategy:
    cluster, overrides = _load(config_data, KEY_CPU_BURST)
    merged = _merged(cluster, overrides, node_labels or {})
    inner = merged.get("cpuBurstConfig", merged)
    return crds.CPUBurstStrategy(
        policy=inner.get("policy", "none"),
        cpu_burst_percent=inner.get("cpuBurstPercent", 1000),
        cfs_quota_burst_percent=inner.get("cfsQuotaBurstPercent", 300),
        cfs_quota_burst_period_seconds=inner.get("cfsQuotaBurstPeriodSeconds", -1),
        share_pool_threshold_percent=merged.get("sharePoolThresholdPercent", 50),
    )


def validate_config_data(config_data: Mapping[str, str]) -> list[str]:
    """ConfigMap admission validation (sloconfig/validator.go): JSON
    well-formedness + percent ranges. Returns error strings (empty = valid)."""
    errors: list[str] = []
    for key in (KEY_COLOCATION, KEY_RESOURCE_THRESHOLD, KEY_RESOURCE_QOS,
                KEY_CPU_BURST, KEY_SYSTEM):
        raw = config_data.get(key, "")
        if not raw:
            continue
        try:
            parsed = json.loads(raw)
        except json.JSONDecodeError as e:
            errors.append(f"{key}: invalid JSON: {e}")
            continue
        if not isinstance(parsed, dict):
            errors.append(f"{key}: must be a JSON object")
            continue
        for name, value in _iter_percents(parsed):
            if not 0 <= value <= 100 and "Burst" not in name:
                errors.append(f"{key}.{name}: percent {value} out of [0,100]")
    cc = config_data.get(KEY_COLOCATION)
    if cc:
        try:
            parsed = json.loads(cc)
            if isinstance(parsed, dict):
                cpu_r = parsed.get("cpuReclaimThresholdPercent")
                if cpu_r is not None and not 0 <= cpu_r <= 100:
                    errors.append("colocation cpuReclaimThresholdPercent out of range")
        except json.JSONDecodeError:
            pass
    return errors


def _iter_percents(obj: dict, prefix: str = ""):
    for k, v in obj.items():
        name = f"{prefix}{k}"
        if isinstance(v, dict):
            yield from _iter_percents(v, name + ".")
        elif isinstance(v, (int, float)) and k.endswith("Percent"):
            yield name, v


def load_config_file(path: str) -> dict[str, str]:
    """Read a YAML file shaped like the slo-controller-config ConfigMap's
    DATA (keys: colocation-config, resource-threshold-config, ...; values
    either JSON strings, as in a real CM, or nested YAML objects, which
    serialize the same way) — the koord-manager --sloconfig-file
    bootstrap seam.  Raises ValueError on a non-mapping document or any
    validation error; the caller decides how loud to die."""
    import yaml

    with open(path) as f:
        raw = yaml.safe_load(f) or {}
    if not isinstance(raw, dict):
        raise ValueError(f"{path}: expected a mapping of ConfigMap "
                         f"data keys")
    config_data = {
        key: (value if isinstance(value, str) else json.dumps(value))
        for key, value in raw.items()
    }
    errors = validate_config_data(config_data)
    if errors:
        raise ValueError(f"{path}: invalid slo config: "
                         + "; ".join(errors))
    return config_data
