"""The §3.2 colocation loop's manager leg, over the wire.

Reference shape (closed binary-to-binary here the way the reference
closes it through the apiserver):

    koordlet:   NodeMetric usage  -> apiserver       (here: sidecar
                                                      node_usage frames)
    manager:    noderesource_controller.go:71 Reconcile
                -> plugins/batchresource/plugin.go:188
                -> PATCH node.status.allocatable[batch-cpu...]
                                                     (here: a
                                                      node_allocatable
                                                      push)
    scheduler:  informer picks up the new allocatable -> BE pods
                schedule against it                  (here: the
                                                      SchedulerBinding
                                                      applies the delta
                                                      to device rows)

:class:`ManagerSyncBinding` is the manager's informer view: a deltasync
binding that tracks every node's base capacity and the koordlet-reported
usage vectors.  :class:`ColocationLoop` turns that view into
:class:`NodeRecord` rows, runs the batched reconcile
(manager/noderesource_controller.py), and pushes each patch back as a
``node_allocatable`` event — the merge event that cannot clobber the
koordlet's device inventory the way a full node_upsert would.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

import numpy as np

from koordinator_tpu.api import crds
from koordinator_tpu.api.resources import ResourceDim
from koordinator_tpu.manager.noderesource_controller import (
    NodeRecord,
    NodeResourceController,
)

MIB = 1 << 20


class _NodeView:
    __slots__ = ("allocatable", "labels", "annotations", "usage",
                 "sys_usage", "hp_usage", "hp_request", "hp_max_used_req",
                 "usage_time")

    def __init__(self):
        self.allocatable: Optional[np.ndarray] = None
        self.labels: dict = {}
        self.annotations: dict = {}
        self.usage: Optional[np.ndarray] = None
        self.sys_usage: Optional[np.ndarray] = None
        self.hp_usage: Optional[np.ndarray] = None
        #: HP (Prod+Mid) pod REQUEST sum and per-pod max(request, usage)
        #: sum — the request/maxUsageRequest calculate policies' inputs;
        #: without them a wire-fed record computes batch capacity as if
        #: no HP pod had requested anything
        self.hp_request: Optional[np.ndarray] = None
        self.hp_max_used_req: Optional[np.ndarray] = None
        self.usage_time: float = 0.0


class ManagerSyncBinding:
    """Manager-side deltasync binding (the watch half of the loop).

    Only node events matter to the noderesource reconcile; pod and
    reservation events are accepted and dropped (the binding contract
    requires every handler).  Thread-safety: deltas apply on the
    RpcClient reader thread while ``ColocationLoop.tick`` reads on the
    caller's — one lock, same discipline as SchedulerBinding.
    """

    #: service attribution for sync-apply spans (deltasync
    #: _dispatch_event): a traced pod/node event applying here shows up
    #: as the MANAGER's hop in the pod's end-to-end trace
    service_name = "manager"

    def __init__(self, clock=time.time):
        self.clock = clock
        self.lock = threading.Lock()
        self.nodes: dict[str, _NodeView] = {}
        #: NodeRecord instances persist across ticks: the controller's
        #: diff-threshold suppression lives in last_batch_* fields
        self.records: dict[str, NodeRecord] = {}

    def reset(self) -> None:
        with self.lock:
            self.nodes.clear()
            self.records.clear()

    def _merge_usage(self, view: _NodeView, entry: dict,
                     arrs: dict) -> None:
        """ONE copy of the usage-field merge for live node_usage deltas
        AND the merged arrays a bootstrap snapshot replays inside
        node_upsert — a field added to one path but not the other would
        silently desynchronize replayed records from live ones (the
        hp_request/hp_max_used_req lockstep edit that motivated this).

        Dates the usage by the KOORDLET's report time when the doc
        carries one: stamping apply-time would make a stale node look
        fresh for a whole degrade window after a manager restart +
        snapshot replay.  Explicit None check — a report_time of 0.0 is
        a valid (infinitely stale) timestamp, not an absent one."""
        view.usage = np.asarray(arrs["usage"], np.int32)
        for field in ("sys_usage", "hp_usage", "hp_request",
                      "hp_max_used_req"):
            if field in arrs:
                setattr(view, field, np.asarray(arrs[field], np.int32))
        report_time = entry.get("usage_time")
        view.usage_time = (float(report_time) if report_time is not None
                           else self.clock())

    def node_upsert(self, entry: dict, arrs: dict) -> None:
        with self.lock:
            view = self.nodes.setdefault(entry["name"], _NodeView())
            view.allocatable = np.asarray(arrs["allocatable"], np.int32)
            view.labels = dict(entry.get("labels", {}))
            view.annotations = dict(entry.get("annotations") or {})
            # a bootstrap snapshot replays merged node_usage arrays
            # inside the upsert — dropping them here would compute
            # HP.Used/System as 0 after a manager restart and
            # over-advertise batch capacity for a report interval
            if "usage" in arrs:
                self._merge_usage(view, entry, arrs)
            # an upsert REPLACES the stored doc wholesale, wiping batch
            # dims from the scheduler's allocatable — the record's
            # diff-suppression state must not survive it, or the
            # controller would suppress the re-push (old == new) and
            # leave batch capacity at 0 until usage drifts
            self.records.pop(entry["name"], None)

    def node_usage(self, entry: dict, arrs: dict) -> None:
        with self.lock:
            view = self.nodes.get(entry["name"])
            if view is None:
                return
            self._merge_usage(view, entry, arrs)

    def node_alloc(self, entry: dict, arrs: dict) -> None:
        # our own patches echo back as deltas; base capacity dims
        # (CPU/MEMORY) are untouched by the batch/mid patch, so applying
        # the echo cannot feed back into the formula
        with self.lock:
            view = self.nodes.get(entry["name"])
            if view is None:
                return
            view.allocatable = np.asarray(arrs["allocatable"], np.int32)

    def node_remove(self, name: str) -> None:
        with self.lock:
            self.nodes.pop(name, None)
            self.records.pop(name, None)

    # non-node events: the reconcile does not consume them
    def node_devices(self, entry: dict) -> None:
        pass

    def pod_add(self, entry: dict, arrs: dict) -> None:
        pass

    def pod_remove(self, name: str) -> None:
        pass

    def reservation_upsert(self, entry: dict, arrs: dict) -> None:
        pass

    def reservation_remove(self, name: str) -> None:
        pass


class ColocationLoop:
    """view -> NodeRecords -> batched reconcile -> node_allocatable push.

    ``push_fn(name, allocatable)`` is the transport seam: the manager
    binary wires it to a STATE_PUSH call on its sidecar client; tests
    can call the service directly.  Tick-driven like the koordlet's
    Daemon — the shell owns the cadence (``run`` is the convenience
    loop for real deployments)."""

    def __init__(self, controller: NodeResourceController,
                 binding: ManagerSyncBinding,
                 push_fn: Callable[[str, np.ndarray], None],
                 ensure_fn: Optional[Callable[[], object]] = None,
                 forecast=None):
        self.controller = controller
        self.binding = binding
        self.push_fn = push_fn
        #: reconnect seam: called at tick start so a dead watch
        #: connection heals even on ticks that push nothing (the push
        #: path alone would only reconnect when a patch fires)
        self.ensure_fn = ensure_fn
        #: predictive-colocation seam (ISSUE 15): a
        #: forecast.colocation.PredictiveColocation that raises each
        #: record's HP peak to the plane's prediction before the
        #: reconcile, so the pushed batch/mid allocatable shrinks ahead
        #: of the forecast LS ramp.  None (the default) reconciles
        #: byte-identically to the reactive loop.
        self.forecast = forecast
        self.ticks = 0
        self.push_failures = 0
        self.connect_failures = 0
        self._stop = threading.Event()

    def _build_records(self) -> list[NodeRecord]:
        cpu, mem = int(ResourceDim.CPU), int(ResourceDim.MEMORY)
        records = []
        with self.binding.lock:
            for name, view in self.binding.nodes.items():
                if view.allocatable is None:
                    continue
                record = self.binding.records.get(name)
                if record is None:
                    record = self.binding.records[name] = NodeRecord(
                        name=name, cpu_capacity_milli=0,
                        mem_capacity_mib=0)
                record.cpu_capacity_milli = int(view.allocatable[cpu])
                record.mem_capacity_mib = int(view.allocatable[mem])
                record.labels = dict(view.labels)
                record.annotations = dict(view.annotations)
                usage = (view.usage if view.usage is not None
                         else np.zeros_like(view.allocatable))
                sys_u = (view.sys_usage if view.sys_usage is not None
                         else np.zeros_like(usage))
                record.metric = (None if view.usage is None
                                 else crds.NodeMetricStatus(
                                     update_time=view.usage_time,
                                     node_usage=crds.ResourceUsage(
                                         cpu_milli=int(usage[cpu]),
                                         memory_bytes=int(usage[mem]) * MIB),
                                     system_usage=crds.ResourceUsage(
                                         cpu_milli=int(sys_u[cpu]),
                                         memory_bytes=int(sys_u[mem]) * MIB),
                                 ))
                hp = view.hp_usage
                record.hp_used_cpu_milli = (
                    None if hp is None else int(hp[cpu]))
                record.hp_used_mem_mib = (
                    None if hp is None else int(hp[mem]))
                # request/maxUsageRequest policy inputs: wire-fed records
                # have no per-pod NodeMetric rows, so the aggregates ride
                # the node_usage report (0 when the koordlet predates them
                # — the old over-advertising behavior, explicit here)
                hp_req = view.hp_request
                record.hp_request_cpu_milli = (
                    0 if hp_req is None else int(hp_req[cpu]))
                record.hp_request_mem_mib = (
                    0 if hp_req is None else int(hp_req[mem]))
                hp_max = view.hp_max_used_req
                record.hp_max_used_req_cpu_milli = (
                    0 if hp_max is None else int(hp_max[cpu]))
                record.hp_max_used_req_mem_mib = (
                    0 if hp_max is None else int(hp_max[mem]))
                records.append(record)
        if self.forecast is not None:
            # outside the binding lock: the records are host-local by
            # now, and the plane holds its own lock for the host copy
            for record in records:
                self.forecast.apply(record)
        return records

    def tick(self) -> int:
        """One reconcile round; returns the number of patches pushed.

        Runs inside a ``manager.colocation_tick`` trace span; every
        pushed patch gets a ``manager.colocation_push`` child whose
        context rides the STATE_PUSH frame to the sidecar (the RPC
        client injects the active context), so a scheduler can see WHICH
        manager tick changed a node's batch allocatable."""
        from koordinator_tpu import metrics, tracing

        self.ticks += 1
        with tracing.TRACER.span(
                "manager.colocation_tick", service="manager",
                attributes={"tick": self.ticks}) as tick_span:
            pushed = self._tick_traced(metrics, tracing)
            tick_span.set_attribute("pushed", pushed)
        return pushed

    def _tick_traced(self, metrics, tracing) -> int:
        if self.ensure_fn is not None:
            try:
                self.ensure_fn()
            except Exception:  # noqa: BLE001 — sidecar down: reconcile
                # over the frozen view anyway, retry next tick
                self.connect_failures += 1
                metrics.colocation_connect_failures_total.inc()
        records = self._build_records()
        patches = self.controller.reconcile(records)
        pushed = 0
        for patch in patches:
            with self.binding.lock:
                view = self.binding.nodes.get(patch.name)
                if view is None or view.allocatable is None:
                    continue
                allocatable = view.allocatable.copy()
            allocatable[ResourceDim.BATCH_CPU] = patch.batch_cpu_milli
            allocatable[ResourceDim.BATCH_MEMORY] = patch.batch_mem_mib
            allocatable[ResourceDim.MID_CPU] = patch.mid_cpu_milli
            allocatable[ResourceDim.MID_MEMORY] = patch.mid_mem_mib
            try:
                with tracing.TRACER.span(
                        "manager.colocation_push", service="manager",
                        attributes={"node": patch.name}):
                    self.push_fn(patch.name, allocatable)
                pushed += 1
                metrics.colocation_patches_total.inc()
            except Exception:  # noqa: BLE001 — a wedged sidecar costs
                # this patch, not the loop; the diff state was already
                # stamped, so force a re-sync next tick.  last_degraded
                # must reset too: the degraded-suppression branch in
                # reconcile() checks it INSTEAD of last_batch_cpu, so a
                # dropped zeroing patch would otherwise never retry and
                # the scheduler would keep advertising batch capacity on
                # a node with expired metrics
                self.push_failures += 1
                metrics.colocation_push_failures_total.inc()
                record = self.binding.records.get(patch.name)
                if record is not None:
                    record.last_batch_cpu = -1
                    record.last_degraded = False
                    record.last_device_resources = None
        return pushed

    def run(self, interval_seconds: float = 60.0) -> None:  # pragma: no cover
        while not self._stop.is_set():
            self.tick()
            self._stop.wait(interval_seconds)

    def stop(self) -> None:
        self._stop.set()
