"""CPU solve-quality check at the north-star shape (no TPU needed).

Runs batch_assign at 50k pods x 10,240 nodes with the approx float-key
candidate path FORCED (the TPU-serving branch; on CPU approx_max_k's
lowering is exact, so this isolates the float-key quantization effect)
and reports assigned counts per (k, spread_bits) variant.  Decides
whether bench.py can flip to k=16 (measured 1.19x on hardware) without
violating the solve_assigned_frac ~ 1.0 quality guard.
"""
import sys
import time

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

from __graft_entry__ import _build_problem
from koordinator_tpu.ops.batch_assign import batch_assign

N_NODES, N_PODS = (10_240, 50_000) if len(sys.argv) < 2 else (
    int(sys.argv[1]), int(sys.argv[2]))

state, pods, cfg = _build_problem(N_NODES, N_PODS, seed=42)
valid = int(np.asarray(pods.valid).sum())
print(f"shape: {N_PODS} pods x {N_NODES} nodes, valid={valid}", flush=True)

VARIANTS = [
    ("k32_strat", dict(k=32, method="approx")),
    ("k16_strat", dict(k=16, method="approx")),
]
for name, kw in VARIANTS:
    t0 = time.perf_counter()
    asn, st = jax.jit(
        lambda s, kw=kw: batch_assign(s, pods, cfg, **kw)[:2])(state)
    asn = np.asarray(asn)
    n = int((asn >= 0).sum())
    used = np.asarray(st.node_requested)
    ok = bool((used <= np.asarray(st.node_allocatable)).all())
    print(f"{name}: assigned {n}/{valid} ({n/valid:.4f})  "
          f"capacity_ok={ok}  wall {time.perf_counter()-t0:.0f}s",
          flush=True)
