"""Candidate-recall measurement: approx_max_k vs exact top_k (VERDICT r4
next #6).

``method="auto"`` serves ``approx`` (``jax.lax.approx_max_k``,
recall_target=0.95) on TPU, but the CPU lowering of approx_max_k is exact —
so the 100%-assignment guarantee behind the TPU default has only ever been
validated on a backend where the reduction is NOT approximate.  This script
produces the data that validates (or flips) the default on the backend where
it matters:

- per-pod candidate recall of ``method="approx"`` against ``method="exact"``
  at 2,048 pods x 10,240 nodes (same k, same stratified spread_bits);
- solve quality (assigned fraction + mean chosen node score) for both
  methods at that shape;
- assigned fraction at the 50k x 10,240 north-star shape for approx and
  chunked (exact too when the backend has the memory for the (P, N)
  materialization — guarded, skipped on OOM).

Decision rule recorded alongside the data: if at-shape
``assigned_frac_approx`` < 0.99 on TPU, flip ``batch_assign``'s
``method="auto"`` TPU arm to "chunked"-with-exact-reduction or "exact"
(ops/batch_assign.py:284) and re-measure.

Prints ONE JSON line.  Env knobs KOORD_RECALL_NODES / KOORD_RECALL_PODS /
KOORD_RECALL_SHAPE_PODS shrink the shapes for CI smoke (the at-shape leg is
skipped when KOORD_RECALL_SHAPE_PODS=0).
"""

from __future__ import annotations

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

K = 16


def _chosen_scores(state, pods, cfg, assignments):
    """Mean raw score of each assigned pod's chosen node (score scale of
    ops/scoring.py, before ranking-key quantization)."""
    from koordinator_tpu.ops.assignment import score_pods

    scores, _ = jax.jit(score_pods)(state, pods, cfg)
    scores = np.asarray(scores)
    asn = np.asarray(assignments)
    mask = asn >= 0
    if not mask.any():
        return 0.0
    return float(scores[np.arange(len(asn))[mask], asn[mask]].mean())


def _recall_leg(n_nodes: int, n_pods: int, out: dict) -> None:
    from __graft_entry__ import _build_problem
    from koordinator_tpu.ops.batch_assign import batch_assign, select_candidates

    state, pods, cfg = _build_problem(n_nodes, n_pods, seed=42)
    sel = jax.jit(select_candidates, static_argnames=("k", "method"))
    _, exact_nodes = sel(state, pods, cfg, k=K, method="exact")
    _, approx_nodes = sel(state, pods, cfg, k=K, method="approx")
    exact_nodes = np.asarray(exact_nodes)
    approx_nodes = np.asarray(approx_nodes)

    # per-pod recall of the exact candidate SET (strata may duplicate a
    # node across slots; set semantics measure what the rounds can use)
    recalls = np.empty(n_pods, np.float64)
    for i in range(n_pods):
        e = set(exact_nodes[i].tolist())
        a = set(approx_nodes[i].tolist())
        recalls[i] = len(e & a) / max(len(e), 1)
    out[f"candidate_recall_mean_{n_pods}p_{n_nodes}n"] = round(
        float(recalls.mean()), 4)
    out[f"candidate_recall_p10_{n_pods}p_{n_nodes}n"] = round(
        float(np.percentile(recalls, 10)), 4)
    out[f"candidate_recall_min_{n_pods}p_{n_nodes}n"] = round(
        float(recalls.min()), 4)

    solve = jax.jit(batch_assign, static_argnames=("k", "method"))
    valid = float(np.asarray(pods.valid).sum())
    for method in ("exact", "approx"):
        asn, _, _ = solve(state, pods, cfg, k=K, method=method)
        frac = float((np.asarray(asn) >= 0).sum()) / valid
        out[f"assigned_frac_{method}_{n_pods}p_{n_nodes}n"] = round(frac, 4)
        out[f"mean_chosen_score_{method}_{n_pods}p_{n_nodes}n"] = round(
            _chosen_scores(state, pods, cfg, asn), 1)


def _quality_leg(n_nodes: int, n_pods: int, out: dict) -> None:
    """quality_lp vs greedy (ISSUE 13): assigned fraction, per-dim
    capacity slack after the solve, and wall time for both engines at
    one shape — the comparison that used to live in the root-level
    scratch_quality.py / scratch_score_quality.py experiments, promoted
    here with slack and provenance attached.  Plus the topo-gang leg:
    realized plan diameter of the baseline vs the quality planner on a
    seeded 2x2x2 topology."""
    import numpy as np

    from __graft_entry__ import _build_problem
    from koordinator_tpu.api.resources import ResourceDim
    from koordinator_tpu.ops.batch_assign import batch_assign
    from koordinator_tpu.quality.lp_pack import lp_pack_assign

    state, pods, cfg = _build_problem(n_nodes, n_pods, seed=42)
    valid = float(np.asarray(pods.valid).sum())

    def slack(st):
        free = np.asarray(st.node_allocatable - st.node_requested)
        alloc = np.asarray(st.node_allocatable)
        node_valid = np.asarray(st.node_valid)
        return {
            dim.name.lower(): round(
                float(free[node_valid, dim].sum())
                / max(float(alloc[node_valid, dim].sum()), 1.0), 4)
            for dim in ResourceDim
            if float(alloc[node_valid, dim].sum()) > 0
        }

    shape = f"{n_pods}p_{n_nodes}n"
    for name, solve in (
        ("greedy", jax.jit(lambda s: batch_assign(s, pods, cfg)[:2])),
        ("quality_lp", jax.jit(lambda s: lp_pack_assign(s, pods, cfg)[:2])),
    ):
        t0 = time.perf_counter()
        asn, st = solve(state)
        frac = float((np.asarray(asn) >= 0).sum()) / max(valid, 1.0)
        out[f"assigned_frac_{name}_{shape}"] = round(frac, 4)
        out[f"capacity_slack_{name}_{shape}"] = slack(st)
        out[f"wall_s_{name}_{shape}"] = round(
            time.perf_counter() - t0, 2)

    # topo-gang diameter: baseline vs quality planner on a seeded tree
    from koordinator_tpu.ops.network_topology import (
        TopologyRequirements,
        TopologyTree,
        plan_gang_placement,
    )
    from koordinator_tpu.quality.topo_gang import (
        plan_diameter,
        plan_gang_placement_quality,
    )
    from koordinator_tpu.state.cluster_state import ClusterState, PodBatch

    rng = np.random.default_rng(42)
    tree = TopologyTree(["spine", "block", "node"])
    t_nodes = 8
    for i in range(t_nodes):
        tree.add_node([f"s{i // 4}", f"b{i // 2}", f"n{i}"])
    topo = tree.build()
    alloc = np.zeros((t_nodes, jnp.asarray(pods.requests).shape[1]),
                     np.int32)
    alloc[:, 0] = rng.integers(2_000, 9_000, t_nodes)
    alloc[:, 1] = 65_536
    t_state = ClusterState.from_arrays(alloc)
    members = 3
    req = np.zeros((members, alloc.shape[1]), np.int32)
    req[:, 0] = 2_000
    req[:, 1] = 1_024
    g_pods = PodBatch.build(req, node_capacity=t_nodes)
    mask = np.zeros(g_pods.capacity, bool)
    mask[:members] = True
    existing = jnp.asarray(rng.integers(0, 2, t_nodes).astype(np.int32))
    treq = TopologyRequirements(desired_slots=members)
    for name, plan_fn in (("baseline", plan_gang_placement),
                          ("quality", plan_gang_placement_quality)):
        plan = plan_fn(t_state, g_pods, mask, topo, treq,
                       node_existing=existing)
        out[f"gang_topo_diameter_{name}"] = plan_diameter(plan, topo)


def _at_shape_leg(n_nodes: int, n_pods: int, out: dict) -> None:
    from __graft_entry__ import _build_problem
    from koordinator_tpu.ops.batch_assign import batch_assign

    state, pods, cfg = _build_problem(n_nodes, n_pods, seed=42)
    valid = float(np.asarray(pods.valid).sum())
    solve = jax.jit(batch_assign, static_argnames=("k", "method"))
    # exact last: it is the one that can OOM (full (P, N) materialization)
    for method in ("approx", "chunked", "chunked_exact", "exact"):
        try:
            t0 = time.perf_counter()
            asn, _, _ = solve(state, pods, cfg, k=K, method=method)
            frac = float((np.asarray(asn) >= 0).sum()) / valid
            out[f"shape_assigned_frac_{method}_{n_pods}p_{n_nodes}n"] = (
                round(frac, 4))
            out[f"shape_wall_s_{method}_{n_pods}p_{n_nodes}n"] = round(
                time.perf_counter() - t0, 1)
        except Exception as e:
            out[f"shape_{method}_error"] = repr(e)[:200]


def main() -> None:
    from bench import _git_head

    n_nodes = int(os.environ.get("KOORD_RECALL_NODES", "10240"))
    n_pods = int(os.environ.get("KOORD_RECALL_PODS", "2048"))
    shape_pods = int(os.environ.get("KOORD_RECALL_SHAPE_PODS", "50000"))

    out: dict = {
        "backend": jax.default_backend(),
        "provenance": _git_head(),
        "k": K,
        "note": "approx_max_k recall vs exact top_k; CPU lowering of "
                "approx_max_k is exact, so only a tpu backend row "
                "validates the method='auto' TPU default",
        "decision_rule": "flip auto's TPU arm from 'approx' to "
                         "'chunked_exact' if shape_assigned_frac_approx "
                         "< 0.99 on tpu",
    }
    _recall_leg(n_nodes, n_pods, out)
    # quality leg at the recall shape (KOORD_RECALL_QUALITY=0 skips):
    # the solve-quality comparison ROADMAP item 4 benches against —
    # assigned fraction + capacity slack per dim + gang topo diameter
    if int(os.environ.get("KOORD_RECALL_QUALITY", "1")):
        _quality_leg(n_nodes, n_pods, out)
    if shape_pods:
        _at_shape_leg(n_nodes, shape_pods, out)
    print(json.dumps(out))


if __name__ == "__main__":
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    main()
